"""Epoch loops (SURVEY.md §2 component 1: ``train()``/``validate()``).

Host-side orchestration only — all math lives in the jitted step. The loop
overlaps host batch packing with device execution naturally: dispatching a
jitted step is async, so packing batch k+1 proceeds while the device runs
batch k. Timing meters separate data time from step time, like the
reference's console output.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import Callable, Iterable, Sequence

import jax
import numpy as np

from cgnn_tpu.data.graph import (
    CrystalGraph,
    GraphBatch,
    PaddingStats,
    batch_iterator,
    batch_shape_key,
    bucketed_batch_iterator,
    capacities_for,  # re-exported; moved to data/graph.py
    round_to_bucket,
)
import jax.numpy as jnp

from cgnn_tpu.train.metrics import (
    AverageMeter,
    accumulate_on_device,
    fetch_device_sums,
    means_from_sums,
)

# In-flight dispatch window (backpressure depth) for the epoch drivers here
# and in parallel.data_parallel. The fence cadence bounds live staged
# batches at 2*_WINDOW (not _WINDOW+1): that is intentional — one fence per
# _WINDOW steps instead of per step halves link round trips — but it doubles
# peak HBM held by staged batches, so memory-tight large-capacity configs
# can shrink it via the environment (CGNN_TPU_WINDOW=2 bounds staging at 4
# batches at the cost of more frequent fences).
try:
    _WINDOW = int(os.environ.get("CGNN_TPU_WINDOW", "8"))
except ValueError:
    import warnings

    warnings.warn("CGNN_TPU_WINDOW must be a positive integer; using 8")
    _WINDOW = 8
if _WINDOW < 1:
    import warnings

    warnings.warn("CGNN_TPU_WINDOW must be >= 1; clamping to 1")
    _WINDOW = 1
from cgnn_tpu.observe import Telemetry
from cgnn_tpu.observe.gauges import device_hbm_table_bytes
from cgnn_tpu.resilience import faultinject
from cgnn_tpu.train.state import TrainState
from cgnn_tpu.train.step import (
    TRAIN_STEP_DONATE,
    jit_train_step,
    make_eval_step,
    make_train_step,
)

# fraction of HBM the staged dataset may claim — the rest is params, opt
# state, activations, XLA workspace, and the scan driver's staged perms
# (the per-kind capacity table lives in observe.gauges, shared with the
# HBM gauges; jax's memory_stats() returns None on this runtime)
_STAGE_FRACTION = 0.8


def staged_nbytes(batches) -> int:
    """Total bytes the batch pytrees would occupy staged on device — the
    ONE definition both fit() and fit_data_parallel feed the capacity
    precheck (what counts toward the budget must not diverge)."""
    return sum(
        x.nbytes for b in batches for x in jax.tree_util.tree_leaves(b)
    )


def device_hbm_budget(device=None) -> int | None:
    """Usable staging budget in bytes for ``device`` (None = unknown)."""
    device = device or jax.devices()[0]
    stats = None
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — backend-dependent, best-effort
        pass
    if stats and "bytes_limit" in stats:
        free = int(stats["bytes_limit"]) - int(stats.get("bytes_in_use", 0))
        return int(free * _STAGE_FRACTION)
    total = device_hbm_table_bytes(getattr(device, "device_kind", ""))
    return None if total is None else int(total * _STAGE_FRACTION)


def check_device_resident_fit(staged_bytes: int, n_devices: int = 1,
                              log_fn: Callable = print) -> bool:
    """True when ``staged_bytes`` fits the device-resident budget.

    False (with a LOUD explanation of the fallback and the knobs that
    shrink staging) means the caller should keep batches host-side and
    restage per epoch (``pack_once`` semantics) instead of dying in an
    opaque XLA OOM mid-staging. Unknown budgets (CPU test meshes, exotic
    devices) pass — the check never blocks platforms it cannot size.
    """
    budget = device_hbm_budget()
    if budget is None:
        return True
    per_device = staged_bytes / max(n_devices, 1)
    if per_device <= budget:
        return True
    log_fn(
        f"device-resident staging needs {per_device / 1e9:.1f} GB/device "
        f"but only ~{budget / 1e9:.1f} GB of HBM is budgeted for data "
        f"({_STAGE_FRACTION:.0%} of "
        f"{getattr(jax.devices()[0], 'device_kind', 'device')} capacity): "
        f"FALLING BACK to host-side pack-once staging (per-step H2D each "
        f"epoch). To stage on-device: --compact-staging (~12x smaller; "
        f"single-device runs today), more data-parallel devices, or a "
        f"smaller dataset/batch capacity."
    )
    return False


def save_preempted_mid_epoch(state, epoch: int, on_epoch_end,
                             log_fn: Callable) -> None:
    """Chunk-boundary preemption: the epoch is partial, so checkpoint
    the CURRENT weights under the last COMPLETED epoch — resume then
    redoes this epoch instead of skipping its unseen tail. Shared by
    ``fit`` and ``parallel.fit_data_parallel`` (the recovery protocol
    must not diverge between the single-host and DP loops)."""
    log_fn(
        f"preemption: epoch {epoch} stopped at a chunk boundary; saving "
        f"resumable checkpoint (epoch {epoch - 1})"
    )
    if on_epoch_end is not None:
        on_epoch_end(state, epoch - 1, {}, False)


def resilience_epoch_end(state, epoch: int, train_m: dict, val_m: dict,
                         is_best: bool, *, monitor, on_epoch_end, preempt,
                         log_fn: Callable):
    """The epoch-boundary resilience protocol shared by ``fit`` and
    ``parallel.fit_data_parallel``: divergence check BEFORE the save (a
    diverged epoch's state must not overwrite the last good checkpoint),
    the save itself, injected-SIGTERM delivery, and the preemption poll.
    -> (state, rolled_back, preempted)."""
    rolled_back = False
    if monitor is not None:
        state, rolled_back = monitor.observe(state, epoch, train_m)
    if on_epoch_end is not None and not rolled_back:
        on_epoch_end(state, epoch, val_m, is_best)
    faultinject.maybe_sigterm(epoch)
    preempted = preempt is not None and preempt.requested
    if preempted:
        if rolled_back:
            # the diverged epoch was NOT saved (by design) — don't tell
            # the operator a boundary checkpoint exists for it
            log_fn(
                f"preemption: stopping after epoch {epoch} — the epoch "
                f"diverged and was not saved; resume restarts from the "
                f"last good checkpoint"
            )
        else:
            log_fn(
                f"preemption: stopping after epoch {epoch} (checkpoint "
                f"saved at the epoch boundary)"
            )
    return state, rolled_back, preempted


def run_epoch(
    step_fn: Callable,
    state: TrainState,
    batches: Iterable[GraphBatch],
    train: bool,
    print_freq: int = 0,
    epoch: int = 0,
    log_fn: Callable = print,
    telemetry: Telemetry | None = None,
) -> tuple[TrainState, dict]:
    """Drive one epoch; returns (state, aggregated metric means).

    Metric sums accumulate ON DEVICE (a dispatched add per step) and are
    fetched once at epoch end — a per-step ``device_get`` would insert a
    host<->device round trip into every step, which dominates epoch time
    whenever link latency is nontrivial (remote/tunneled accelerators) and
    throttles dispatch pipelining everywhere else. A sliding window of
    in-flight step results provides backpressure (bounds how many staged
    batches can hold live HBM buffers ahead of execution): once
    ``2 * _WINDOW`` results are in flight, ONE scalar from ``_WINDOW``
    dispatches ago is VALUE-FETCHED — a true data dependency, unlike
    ``block_until_ready``, which this machine's tunneled runtime satisfies
    before execution completes — proving every earlier step finished, so
    at most ``2 * _WINDOW`` batches hold live buffers. One fence per
    ``_WINDOW`` steps, NOT per step: each fetch costs a full link round
    trip (~75 ms on the tunnel; the per-step fence made this loop 4-5x
    slower than the scan driver — SCAN_COST.json r4). ``batch_time``
    reports the wall-clock mean per step over each sync window (dispatch
    is async, so a per-dispatch stopwatch would read zero); ``data_time``
    is host wait per batch as before.
    """
    from collections import deque

    meters = {
        "batch_time": AverageMeter(),
        "data_time": AverageMeter(),
    }
    dev_sums: dict | None = None
    inflight: deque = deque()
    window_t0 = time.perf_counter()
    window_steps = 0
    end = time.perf_counter()
    it = -1

    def _sync_window(now):
        nonlocal window_t0, window_steps
        if window_steps:
            meters["batch_time"].update(
                (now - window_t0) / window_steps, n=window_steps
            )
        window_t0, window_steps = now, 0

    for it, batch in enumerate(batches):
        meters["data_time"].update(time.perf_counter() - end)
        if train:
            state, metrics = step_fn(state, batch)
        else:
            metrics = step_fn(state, batch)
        dev_sums = accumulate_on_device(dev_sums, metrics)
        inflight.append(next(iter(metrics.values())))
        if len(inflight) >= 2 * _WINDOW:
            # ONE fence per _WINDOW steps, not per step: each value fetch
            # is a full link round trip (~75 ms on the tunneled runtime —
            # a per-step fence made this loop 4-5x slower than the scan
            # driver at bench scale, SCAN_COST.json r4). Fetching the
            # _WINDOW-th-oldest handle proves every step before it
            # finished, so at most 2*_WINDOW batches hold live HBM
            # buffers ahead of execution.
            for _ in range(_WINDOW - 1):
                inflight.popleft()
            jax.device_get(inflight.popleft())  # true fence, see docstring
        window_steps += 1
        end = time.perf_counter()
        if print_freq and it % print_freq == 0:
            sums = fetch_device_sums(dev_sums)
            _sync_window(time.perf_counter())
            count = max(sums.get("count", 1.0), 1.0)
            parts = [
                f"{'Epoch' if train else 'Val'}: [{epoch}][{it}]",
                f"Time/step {meters['batch_time'].val:.3f} ({meters['batch_time'].avg:.3f})",
                f"Data {meters['data_time'].val:.3f} ({meters['data_time'].avg:.3f})",
                f"Loss {sums.get('loss_sum', 0.0) / count:.4f}",
            ]
            if "mae_sum" in sums:
                parts.append(f"MAE {sums['mae_sum'] / count:.4f}")
            if "force_mae_sum" in sums:
                fcount = max(sums.get("force_mae_count", 1.0), 1.0)
                parts.append(f"F-MAE {sums['force_mae_sum'] / fcount:.4f}")
            if "correct_sum" in sums:
                parts.append(f"Acc {sums['correct_sum'] / count:.4f}")
            log_fn("  ".join(parts))
    sums = fetch_device_sums(dev_sums)
    _sync_window(time.perf_counter())
    if telemetry is not None:
        # dispatch-share + host-wait counters (flushed in the run summary)
        telemetry.counter_add("per_step_steps", it + 1)
        telemetry.counter_add("data_wait_s", meters["data_time"].sum)
    return state, means_from_sums(sums, it + 1)


def profile_wrap(iterator, profile_steps: int, profile_dir: str,
                 log_fn: Callable = print):
    """Trace steps [1, 1+profile_steps) of ``iterator`` (step 0 is the
    compile step; tracing it would swamp the timeline). Shared by the
    single-device and data-parallel epoch loops."""
    if not profile_steps:
        yield from iterator
        return
    tracing = False
    try:
        for i, b in enumerate(iterator):
            if i == 1:
                jax.profiler.start_trace(profile_dir or "profile")
                tracing = True
            yield b
            if tracing and i >= profile_steps:
                jax.profiler.stop_trace()
                tracing = False
                log_fn(f"profiler trace written to {profile_dir}")
    finally:
        if tracing:
            jax.profiler.stop_trace()


class PackOncePlan:
    """pack_once / device_resident epoch staging, shared by ``fit`` and
    ``parallel.fit_data_parallel``: pack every batch on the first epoch,
    reshuffle BATCH order (not graph membership) on later epochs, and —
    with ``device_resident`` — stage each batch's buffers on device once
    so later epochs incur zero host->device traffic."""

    def __init__(
        self,
        make_train_batches: Callable,
        make_val_batches: Callable,
        rng: np.random.Generator,
        device_resident: bool = False,
        stage: Callable | None = None,
    ):
        self._make_train = make_train_batches
        self._make_val = make_val_batches
        self._rng = rng
        self._device_resident = device_resident
        self._stage = stage if stage is not None else jax.device_put
        self._train: list | None = None
        self._val: list | None = None

    def epoch_iterators(self) -> tuple[Iterable, Iterable]:
        if self._train is None:
            self._train = list(self._make_train())
            self._val = list(self._make_val())
            if self._device_resident:
                self._train = [self._stage(b) for b in self._train]
                self._val = [self._stage(b) for b in self._val]
            # keep packing order: the first epoch is then bit-identical to
            # the per-epoch-packing path with the same seed
            order = np.arange(len(self._train))
        else:
            order = self._rng.permutation(len(self._train))
        return (self._train[i] for i in order), iter(self._val)


class PendingPairMetrics:
    """A deferred epoch-pair sums fetch running on a background thread
    (ISSUE 5 satellite: SCAN_COST r5 measured ``pair_fetch_s`` at
    224.9 ms of a 256 ms bench-scale epoch — almost all of it the fetch
    WAITING for the epoch's in-flight compute, during which the host sat
    idle instead of dispatching the next epoch).

    ``result()`` joins the thread and returns ``(train_means,
    val_means)`` — the exact values the synchronous path computes, from
    the exact same ``fetch_device_sums`` call (bit-identical, pinned by
    test); an exception from the fetch re-raises at the join."""

    def __init__(self, fn: Callable):
        self._fn = fn
        self._out = None
        self._err: BaseException | None = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="cgnn-pair-fetch"
        )
        self._thread.start()

    def _run(self) -> None:
        try:
            self._out = self._fn()
        except BaseException as e:  # noqa: BLE001 — re-raised at result()
            self._err = e

    def done(self) -> bool:
        return not self._thread.is_alive()

    def result(self):
        self._thread.join()
        if self._err is not None:
            raise self._err
        return self._out


class ScanEpochDriver:
    """Whole-epoch dispatch for device-resident datasets: one ``lax.scan``
    per bucket shape per epoch instead of one dispatch per step.

    On a link with nontrivial dispatch latency (remote/tunneled
    accelerators) the per-step Python dispatch dominates the epoch once
    batches are HBM-resident; folding the steps into a scan reduces an
    epoch to (number of bucket shapes) dispatches + fetches. Batch order
    shuffles via the scanned index array (a device-side dynamic index into
    the stacked batch arrays), grouped by shape — cross-bucket interleaving
    is traded away for the dispatch amortization.
    """

    def __init__(self, train_body: Callable, eval_body: Callable,
                 train_batches: list, val_batches: list,
                 rng: np.random.Generator, stage: Callable | None = None,
                 expand: Callable | None = None,
                 chunk_steps: int | None = None,
                 telemetry: Telemetry | None = None,
                 preempt=None):
        """``stage`` places each stacked group on device (default
        ``jax.device_put``); data-parallel callers pass a mesh-sharding
        stage so the per-step device axis (axis 1 of the stack) lands
        split over the mesh.

        ``expand`` (compact staging, data/compact.py) maps each scanned
        batch to the full GraphBatch INSIDE the jitted scan body — the
        stacked groups then hold the ~12x smaller raw form in HBM and the
        table-gather + Gaussian expansion fuse into each step.

        ``telemetry`` at step level stages the in-scan metric tap
        (observe.stream) into every scan body: per-step scalars ring out
        to the host via an async callback with no fetch on the dispatch
        path and no effect on the donated-carry trajectory. Below step
        level NOTHING is staged — the scanned HLO is identical to a
        telemetry-free build.

        ``preempt`` (a ``resilience.PreemptionHandler``) is polled at
        every CHUNK boundary while driving an epoch: a whole-epoch scan
        can outlast a preemption grace window, so the driver stops
        dispatching further chunks as soon as a checkpoint is requested
        and sets ``self.aborted`` for the caller to save-and-exit."""
        from cgnn_tpu.data import invariants

        if expand is not None:
            tb, eb = train_body, eval_body
            train_body = lambda s, b: tb(s, expand(b))  # noqa: E731
            eval_body = lambda s, b: eb(s, expand(b))  # noqa: E731
        if chunk_steps is not None:
            if chunk_steps < 1:
                raise ValueError(f"chunk_steps must be >= 1, got {chunk_steps}")
            self.chunk_steps = int(chunk_steps)

        # the scan trusts these stacks for a whole training run; validate
        # every input batch (incl. DP-stacked rows) before staging them
        for b in train_batches:
            invariants.maybe_check_any(b, train=True)
        for b in val_batches:
            invariants.maybe_check_any(b)
        self._rng = rng
        self._telemetry = telemetry
        self._preempt = preempt
        # True when the LAST driven epoch stopped early at a chunk
        # boundary on a preemption request (reset per public drive call)
        self.aborted = False
        # True when the last run_epoch_pair's EVAL phase was cut short
        # by preemption (its val means cover only the chunks that ran)
        self.eval_truncated = False
        # the tap is staged into scan bodies ONLY at step-level telemetry
        self._tap = (
            telemetry.tap_metrics
            if telemetry is not None and telemetry.stream is not None
            else None
        )
        self._stage = stage if stage is not None else jax.device_put
        # per-phase wall-clock accounting (scripts/scan_cost.py reads this
        # to attribute the driver's fixed costs); keys are cumulative
        # seconds, reset by the caller when desired
        self.timings: dict[str, float] = {}
        t0 = time.perf_counter()
        self._train_groups = self._stack_groups(train_batches)
        self._val_groups = self._stack_groups(val_batches)
        self.timings["init_stack_stage_s"] = time.perf_counter() - t0
        self._train_body, self._eval_body = train_body, eval_body
        self._train_scans: dict = {}
        self._eval_scans: dict = {}
        # one-epoch-ahead schedules, keyed (id(groups), train, first) —
        # see _build_sched/_drive
        self._sched_cache: dict = {}

    def _stack_groups(self, batches: list) -> dict:
        """Group same-shape batches, stack on a leading axis, stage to HBM.

        Keys on the full (nodes, edges, in_slots) shapes — not the
        capacity scalars — so already-device-stacked DP batches (leading
        device axis) group correctly too."""
        groups: dict = {}
        for b in batches:
            groups.setdefault(batch_shape_key(b), []).append(b)
        return {
            k: self._stage(
                jax.tree_util.tree_map(lambda *xs: np.stack(xs), *bs)
            )
            for k, bs in groups.items()
        }

    # mean steps folded into one dispatch. Small, deliberately: r4
    # measured that dispatch COUNT is essentially free (48 two-step scans
    # run at the rate of 3 thirty-two-step scans — only SYNC points cost,
    # PERF.md 6c), while chunk GRANULARITY is what multi-bucket
    # convergence pays for — at MP-146k, chunk 8's same-shape runs cost
    # ~35% val MAE vs the per-step interleave (0.0599 vs 0.0447, same
    # seed/budget), and chunk 2 recovers it fully (0.0424 at 3.0 s vs
    # 2.7 s epochs; PERF.md 6e). Actual lengths are drawn from
    # {1, 2, 4} and groups picked weighted-randomly (see _drive) so the
    # step sequence tracks the per-step loop's weighted interleave.
    chunk_steps = 2

    def _scan_fn(self, cache: dict, key, body: Callable, train: bool):
        if key not in cache:
            def scan_fn(state, stacked, perm):
                def step(carry, i):
                    batch = jax.tree_util.tree_map(lambda x: x[i], stacked)
                    if train:
                        carry, metrics = body(carry, batch)
                        if self._tap is not None:
                            # per-step scalars ring out to the host from
                            # INSIDE the scan (async callback; no fetch,
                            # no change to the donated carry)
                            self._tap(metrics, "train", step=carry.step)
                    else:
                        metrics = body(carry, batch)
                        if self._tap is not None:
                            self._tap(metrics, "eval")
                    return carry, metrics

                state2, ms = jax.lax.scan(step, state, perm)
                return state2, jax.tree_util.tree_map(
                    lambda m: m.sum(0), ms
                )

            cache[key] = jax.jit(
                scan_fn,
                donate_argnums=TRAIN_STEP_DONATE if train else (),
            )
        return cache[key]

    # per-group steps reserved for the end of each training epoch and run
    # ONE step at a time, round-robin across groups: BatchNorm's running
    # stats are an EMA with momentum 0.1, so the last ~16 steps carry most
    # of their weight — ending on a single-shape 16-step chunk would skew
    # eval statistics toward one size class (observed: val MAE 2x worse at
    # MP-146k scale until the tail was mixed). Capped at n//4 per group
    # (SCAN_COST.json r4): a FIXED 8-per-group tail turned small epochs
    # into mostly single-step dispatching — at the 18-batch bench scale it
    # was the whole 31.5k-vs-50k gap — while a proportional tail keeps the
    # last few steps shape-mixed at every scale
    mixed_tail = 8

    def _tail_for(self, n: int) -> int:
        return min(self.mixed_tail, max(1, n // 4))

    def _build_sched(self, groups, train, first):
        """(queues, tails, steps) with every chunk perm ALREADY staged on
        device. Called one epoch AHEAD of use (see _drive) so the H2D
        transfer overlaps the in-flight epoch instead of stalling the
        device at the epoch boundary — the trace showed the driver's
        entire fixed cost as one ~90-140 ms device-idle gap at each epoch
        start (sync fetch + perm staging + dispatch latency round trips).
        """
        c = self.chunk_steps
        queues = []
        tails = []
        steps = 0
        pick_order: list[int] = []
        multi = train and len(groups) > 1
        for key, stacked in groups.items():
            n = int(jax.tree_util.tree_leaves(stacked)[0].shape[0])
            tail = self._tail_for(n) if multi else 0
            perm = (
                np.arange(n) if (first or not train)
                else self._rng.permutation(n)
            )
            head, foot = perm[: n - tail], perm[n - tail :]
            if multi:
                # randomized chunk lengths from {c/2, c, 2c} (mean ~c;
                # only 3 distinct compile keys per group): varied lengths
                # + weighted-random group picks below make the step
                # sequence statistically match the per-step weighted
                # interleave instead of the r2 deterministic round-robin
                chunks, i = [], 0
                sizes = [max(1, c // 2), c, 2 * c]
                while i < len(head):
                    rem = len(head) - i
                    # only draw sizes that fit: the final remainder is
                    # then < c/2, so distinct compile keys stay bounded
                    # at {1..c/2-1} + the 3 sizes per group, stable
                    # across epochs (an arbitrary-length remainder would
                    # accumulate up to 2c scan compiles through the
                    # high-latency tunnel)
                    avail = [s for s in sizes if s <= rem]
                    ln = int(self._rng.choice(avail)) if avail else rem
                    chunks.append(head[i : i + ln])
                    i += ln
            else:
                chunks = [head[i : i + c] for i in range(0, len(head), c)]
            if chunks:
                queues.append((key, stacked, chunks))
            if len(foot):
                tails.append((key, stacked, [foot[i : i + 1]
                                             for i in range(len(foot))]))
            steps += n
        # one async transfer for every perm (a per-dispatch jnp.asarray
        # would be a fresh synchronous H2D each time); i32 explicitly —
        # np.arange is i64 and would trace distinct (or x64-invalid) scans
        for entry in queues + tails:
            entry[2][:] = jax.device_put(
                [np.ascontiguousarray(ch, dtype=np.int32)
                 for ch in entry[2]]
            )
        # weighted group-pick sequence, PRECOMPUTED here (ISSUE 9
        # satellite): the per-chunk np.array + rng.choice(p=...) that
        # used to run on the DISPATCH path in run_queues (a measurable
        # host-side fixed cost per chunk — scan_cost.py, PERF.md §6c)
        # moves into the schedule build, which _drive prebuilds one
        # epoch AHEAD so it overlaps the in-flight epoch. Same sampler,
        # same weights (remaining steps per group), same rng stream
        # shape — the step-sequence distribution is unchanged, and the
        # sync-vs-async-fetch bit-identity pin still holds because both
        # paths build schedules in the same order.
        if multi and not first:
            rem = [[len(ch) for ch in entry[2]] for entry in queues]
            alive = list(range(len(queues)))
            while alive:
                if len(alive) > 1:
                    w = np.array([float(sum(rem[i])) for i in alive])
                    gi = alive[int(self._rng.choice(len(alive),
                                                    p=w / w.sum()))]
                else:
                    gi = alive[0]
                pick_order.append(gi)
                rem[gi].pop(0)
                if not rem[gi]:
                    alive.remove(gi)
        return queues, tails, steps, pick_order

    def warm(self, state: TrainState) -> TrainState:
        """Compile every (shape, chunk-length) scan program the driver can
        draw, so no first-compile (seconds through a high-latency link)
        lands inside a caller's timed region (bench.py, scan_cost.py).

        Runs the REAL train bodies (compilation requires execution here),
        but against a disposable on-device copy of ``state``, so the
        ~1+ epochs of optimizer updates on skewed arange%n-repeated batches
        never touch the caller's state: the returned state is the input,
        untrained, with every program the driver can draw sitting in the
        jit cache (keyed on shapes/dtypes, which the copy shares).

        Deterministic by enumeration: chunk lengths come from the bounded
        set {1 .. c/2, c, 2c} (sizes + remainders + tail singles), so each
        is executed once directly — sampling warmup epochs until the
        program set stabilizes can miss a rare length for many epochs when
        ``chunk_steps`` is small.
        """
        # Real buffers, not aliases: the train bodies donate their state
        # argument, so passing the caller's arrays would invalidate them.
        # Copy-THEN-place: jnp.array(x) alone makes the copy but relies on
        # it implicitly keeping x's layout, and jax.device_put(x,
        # x.sharding) alone ALIASES the buffer (measured: same
        # unsafe_buffer_pointer, donation kills the original) — the
        # device_put onto the source sharding makes the replicated/sharded
        # layout explicit on a buffer that is already a fresh copy.
        scratch = jax.tree_util.tree_map(
            lambda x: jax.device_put(jnp.array(x), x.sharding)
            if isinstance(x, jax.Array) else x,
            state,
        )
        c = self.chunk_steps
        lengths = sorted(set(range(1, max(2, c // 2 + 1))) | {c, 2 * c})
        # warmup dispatches run the REAL compiled programs — mute the
        # step stream so compile-time executions don't pollute the
        # per-step record stream
        warm_ctx = (
            self._telemetry.warmup() if self._telemetry is not None
            else contextlib.nullcontext()
        )
        with warm_ctx:
            for key, stacked in self._train_groups.items():
                n = int(jax.tree_util.tree_leaves(stacked)[0].shape[0])
                for ln in lengths:
                    if ln > n:
                        continue
                    fn = self._scan_fn(
                        self._train_scans, (key, ln), self._train_body, True
                    )
                    perm = jax.device_put(
                        np.arange(ln, dtype=np.int32) % n
                    )
                    scratch, _ = fn(scratch, stacked, perm)
            # eval programs + the pair plumbing compile on a normal epoch
            self.run_epoch_pair(scratch, first=True)
        return state

    def _drive(self, state: TrainState, groups, scans, body, train, first,
               prebuild: bool = True):
        """Dispatch one epoch; returns (state, device_sums, steps) WITHOUT
        fetching — callers combine/fetch sums (run_epoch_pair: one link
        sync for train+eval; train_epoch/eval_epoch: per-phase fetch).
        ``prebuild=False`` defers the next-epoch schedule prebuild to the
        caller (run_epoch_pair's async-fetch mode overlaps it with the
        background sums fetch instead)."""
        t_drive0 = time.perf_counter()
        sched_key = (id(groups), train, first)
        if train:
            sched = self._sched_cache.pop(sched_key, None)
            if sched is None:
                sched = self._build_sched(groups, train, first)
        else:
            # the eval schedule is deterministic (first=True, arange
            # perms): build once, reuse every epoch — re-staging identical
            # perms each epoch was pure waste
            sched = self._sched_cache.get(sched_key)
            if sched is None:
                sched = self._build_sched(groups, train, first)
                self._sched_cache[sched_key] = sched
        queues, tails, _planned_steps, pick_order = sched
        # run_queues consumes the chunk lists: work on shallow DEQUE
        # copies (O(1) popleft — pop(0) shifted the whole list per
        # chunk) so the cached eval schedule survives reuse
        queues = [(k, st, collections.deque(ch)) for k, st, ch in queues]
        tails = [(k, st, collections.deque(ch)) for k, st, ch in tails]
        multi = train and len(groups) > 1
        # chunk dispatch is the host-side hot loop (ISSUE 9 satellite —
        # PERF.md §6c): the weighted group picks were PREDRAWN into
        # pick_order by _build_sched (one epoch ahead, overlapping the
        # in-flight epoch), so per chunk this loop does a deque pop, a
        # dict lookup, the dispatch, and one device-side accumulate.
        # Chunk metric sums accumulate ON DEVICE (one fused async add
        # per chunk) and are fetched ONCE, packed into a single array —
        # a list-of-dicts device_get at epoch end moved every scalar as
        # its own link round trip, which at bench scale (17 chunks x 4
        # keys) was ~250 ms/epoch: the whole driver-vs-steady gap
        # (SCAN_COST.json r4; metrics.fetch_device_sums)
        dev_sums: dict | None = None
        n_chunks = 0
        executed = 0
        spans = (self._telemetry.spans
                 if self._telemetry is not None else None)

        def run_queues(qs, weighted):
            nonlocal state, dev_sums, n_chunks, executed
            rr = 0
            picks = iter(pick_order)
            by_index = list(qs)  # pick_order indexes the BUILD order
            while qs:
                if self._preempt is not None and self._preempt.requested:
                    # chunk-boundary preemption: stop dispatching; the
                    # caller saves the (mid-epoch) state and exits with
                    # the resumable code. Metric denominators use the
                    # executed step count, not the planned one.
                    self.aborted = True
                    return
                if weighted and pick_order:
                    entry = by_index[next(picks)]
                else:
                    # round-robin across groups (never drain one bucket
                    # before starting the next: BN's momentum-0.1 EMA and
                    # the optimizer must not see a size-sorted epoch)
                    entry = qs[rr % len(qs)]
                    rr += 1
                key, stacked, chunks = entry
                chunk = chunks.popleft()  # device-staged perm (see above)
                # compile key includes the chunk length (bounded per
                # group: <= 2c distinct lengths, one remainder, length 1)
                fn = self._scan_fn(
                    scans, (key, len(chunk)), body, train
                )
                t0 = time.perf_counter() if spans is not None else 0.0
                state, chunk_sums = fn(state, stacked, chunk)
                if spans is not None:
                    # host-side dispatch cost per chunk, visible in the
                    # Chrome trace next to the device timeline (§6c)
                    spans.complete("scan.chunk", t0, time.perf_counter(),
                                   steps=int(chunk.shape[0]),
                                   train=train)
                dev_sums = accumulate_on_device(dev_sums, chunk_sums)
                n_chunks += 1
                executed += int(chunk.shape[0])
                if not chunks:
                    qs.remove(entry)

        t_sched = time.perf_counter()
        run_queues(queues, weighted=multi and not first)
        t_chunks = time.perf_counter()
        run_queues(tails, weighted=False)  # mixed single-step tail
        t_tail = time.perf_counter()
        # prebuild + stage the NEXT train epoch's schedule while this
        # epoch's dispatches are still executing: its H2D transfers ride
        # along the in-flight work instead of stalling the next epoch's
        # first scan. (If the run ends here the prebuild is unused — a few
        # rng draws consumed in the same order a further epoch would have.)
        if train and not self.aborted and prebuild:
            self._sched_cache[(id(groups), True, False)] = \
                self._build_sched(groups, True, False)
        t_prebuild = time.perf_counter()
        phase = "train" if train else "eval"
        tm = self.timings
        tm[f"{phase}_sched_s"] = tm.get(f"{phase}_sched_s", 0.0) \
            + (t_sched - t_drive0)
        tm[f"{phase}_chunk_dispatch_s"] = tm.get(
            f"{phase}_chunk_dispatch_s", 0.0) + (t_chunks - t_sched)
        tm[f"{phase}_tail_dispatch_s"] = tm.get(
            f"{phase}_tail_dispatch_s", 0.0) + (t_tail - t_chunks)
        tm[f"{phase}_prebuild_s"] = tm.get(f"{phase}_prebuild_s", 0.0) \
            + (t_prebuild - t_tail)
        tm[f"{phase}_dispatches"] = tm.get(f"{phase}_dispatches", 0.0) \
            + n_chunks
        if self._telemetry is not None:
            self._telemetry.counter_add("scan_steps", executed)
            self._telemetry.counter_add(f"scan_{phase}_dispatches", n_chunks)
        return state, dev_sums, executed

    def train_epoch(self, state: TrainState, first: bool):
        self.aborted = False
        state, dev_sums, steps = self._drive(
            state, self._train_groups, self._train_scans,
            self._train_body, train=True, first=first,
        )
        return state, means_from_sums(fetch_device_sums(dev_sums), steps)

    def eval_epoch(self, state: TrainState):
        self.aborted = False
        _, dev_sums, steps = self._drive(
            state, self._val_groups, self._eval_scans,
            self._eval_body, train=False, first=True,
        )
        return means_from_sums(fetch_device_sums(dev_sums), steps)

    def run_epoch_pair(self, state: TrainState, first: bool,
                       async_fetch: bool = False):
        """Train epoch + eval epoch with ONE link sync for both.

        Each fetch on a high-latency link stalls the device for a full
        round trip (the trace's only remaining gap); eval's dispatches
        depend on the post-train state only THROUGH THE DEVICE, so they
        can be enqueued before the train sums are ever fetched —
        halving the per-epoch sync count. -> (state, train_means,
        val_means).

        ``async_fetch=True`` (ISSUE 5 satellite) returns ``(state,
        PendingPairMetrics)`` instead: the sums fetch — SCAN_COST r5's
        ``pair_fetch_s``, 224.9 ms of a 256 ms bench epoch, almost all
        of it waiting for the epoch's in-flight compute — runs on a
        background thread while the caller keeps dispatching (the next
        epoch's first scans in ``fit``), and the next-epoch schedule
        prebuild moves AFTER the fetch thread starts so it overlaps the
        wait too. The rng draw ORDER is unchanged (train draws, then
        prebuild draws; eval consumes none in between), so schedules,
        trajectories, and the fetched metrics are bit-identical to the
        synchronous path — pinned by test.
        """
        self.aborted = False
        self.eval_truncated = False
        state, tr_sums, tr_steps = self._drive(
            state, self._train_groups, self._train_scans,
            self._train_body, train=True, first=first,
            prebuild=not async_fetch,
        )
        train_aborted = self.aborted
        ev_sums, ev_steps = None, 0
        if self._val_groups and not train_aborted:
            # a preempted train epoch skips eval outright: the grace
            # window is for the checkpoint, not for scoring a half epoch
            _, ev_sums, ev_steps = self._drive(
                state, self._val_groups, self._eval_scans,
                self._eval_body, train=False, first=True,
            )
            # a preemption that lands during EVAL must not mark the
            # (fully completed) train epoch aborted — the caller would
            # checkpoint it under epoch-1 and retrain the whole epoch on
            # resume. The epoch completes; eval_truncated tells the
            # caller its val means cover only the eval chunks that ran
            # (so a lucky partial score must not repoint 'best'), and
            # the epoch-boundary preempt check exits after the save.
            self.eval_truncated = self.aborted
            self.aborted = train_aborted
        combined = {f"t:{k}": v for k, v in (tr_sums or {}).items()}
        combined |= {f"e:{k}": v for k, v in (ev_sums or {}).items()}

        def fetch_pair():
            t0 = time.perf_counter()
            fetched = fetch_device_sums(combined or None)
            self.timings["pair_fetch_s"] = self.timings.get(
                "pair_fetch_s", 0.0) + (time.perf_counter() - t0)
            tr = {k[2:]: v for k, v in fetched.items()
                  if k.startswith("t:")}
            ev = {k[2:]: v for k, v in fetched.items()
                  if k.startswith("e:")}
            return (means_from_sums(tr, tr_steps),
                    means_from_sums(ev, ev_steps))

        if not async_fetch:
            train_m, val_m = fetch_pair()
            return state, train_m, val_m
        pending = PendingPairMetrics(fetch_pair)
        # the deferred prebuild (see _drive): schedule + stage the next
        # train epoch while the fetch thread blocks on this epoch's
        # in-flight compute. Same rng draws, same order as the sync path.
        if not train_aborted:
            self._sched_cache[(id(self._train_groups), True, False)] = \
                self._build_sched(self._train_groups, True, False)
        return state, pending


def fit(
    state: TrainState,
    train_graphs: Sequence[CrystalGraph],
    val_graphs: Sequence[CrystalGraph],
    *,
    epochs: int,
    batch_size: int,
    node_cap: int | None = None,
    edge_cap: int | None = None,
    classification: bool = False,
    seed: int = 0,
    print_freq: int = 10,
    on_epoch_end: Callable | None = None,
    log_fn: Callable = print,
    start_epoch: int = 0,
    train_step_fn: Callable | None = None,
    eval_step_fn: Callable | None = None,
    best_metric: str | None = None,
    buckets: int = 1,
    on_epoch_metrics: Callable | None = None,
    profile_steps: int = 0,
    profile_dir: str = "",
    pack_once: bool = False,
    device_resident: bool = False,
    dense_m: int | None = None,
    scan_epochs: bool = False,
    snug: bool = False,
    edge_dtype=np.float32,
    compact=None,
    chunk_steps: int | None = None,
    telemetry: Telemetry | None = None,
    guard: bool = False,
    monitor=None,
    preempt=None,
) -> tuple[TrainState, dict]:
    """Reference ``main()`` loop: train/validate per epoch, track best.

    ``train_step_fn``/``eval_step_fn`` override the default task steps (the
    force task passes its composite-loss steps); ``best_metric`` overrides
    the model-selection metric key (lower-is-better unless classification).
    ``buckets > 1`` batches with per-size-class capacities (at most
    ``buckets`` compiled step shapes) instead of one global capacity.
    ``on_epoch_metrics(epoch, train_m, val_m)`` fires after each epoch (the
    machine-readable metrics hook); ``profile_steps > 0`` wraps that many
    post-compile steps of the first epoch in ``jax.profiler.trace`` writing
    to ``profile_dir``.

    ``pack_once`` packs the training batches on the first epoch and reuses
    them, shuffling BATCH order (not graph membership) across epochs — for
    large cached datasets where per-epoch host packing would starve the
    device (the reference reshuffles graphs per epoch; batch-level
    shuffling is the standard streaming-dataset trade and costs a little
    within-batch randomness for host throughput). Batches stay host-side;
    the prefetcher re-stages them to HBM each epoch.

    ``device_resident`` (implies pack_once) additionally stages every packed
    batch into HBM once and reuses the device buffers across epochs — zero
    per-epoch host->device traffic. For datasets whose packed batches fit
    in HBM alongside the model (MP-146k at batch 512 is ~10 GB); the fix
    for host-link-bound epochs (e.g. a tunneled/remote accelerator).

    ``compact`` (a ``data.compact.CompactSpec``; requires ``scan_epochs``
    and ``dense_m``) stages batches in raw form — atom vocabulary indices
    + scalar distances, ~12x fewer bytes — and rebuilds the GraphBatch
    inside the jitted scan body (data/compact.py). Cuts device-resident
    H2D staging and HBM footprint ~12x; measured neutral on steady-state
    step time (the expansion fuses into the step).

    ``telemetry`` (an ``observe.Telemetry``) wires spans around the
    staging/epoch phases, padding + dispatch gauges, and — at step level
    — the in-scan per-step metric stream plus in-graph grad-health
    metrics. None (or level 'off') changes nothing: no wrapper is applied
    to any step body and no callback is staged into any compiled program.

    ``guard`` wraps the train body with the in-graph divergence guard
    (``resilience.guard.guard_step``): non-finite updates are skipped on
    device; trajectory bit-identical when nothing fires. ``monitor`` (a
    ``resilience.DivergenceMonitor``) is consulted once per epoch and may
    roll the state back to the last good checkpoint with an LR cut.
    ``preempt`` (a ``resilience.PreemptionHandler``) is polled at epoch
    boundaries (chunk boundaries inside the epoch scan): when a signal
    arrived, the loop saves a resumable checkpoint via ``on_epoch_end``,
    stops, and marks the result ``{"preempted": True}``.

    ``scan_epochs`` (implies device_resident) folds the epoch into one
    ``lax.scan`` dispatch per bucket shape (ScanEpochDriver) — measured
    5.5s vs 29s per MP-146k epoch through a high-latency tunnel.
    Single-bucket runs are trajectory-identical to the per-step loop;
    multi-bucket runs use randomized chunk scheduling (r3) and converge
    identically to the per-step loop (scripts/scan_convergence.py:
    val-MAE plateau 0.158-0.159 for both drivers, epoch-by-epoch, vs
    0.024 per-step seed noise) — train.py makes scan the default
    whenever --device-resident is set.
    """
    device_resident = device_resident or scan_epochs
    pack_once = pack_once or device_resident
    if compact is not None and not scan_epochs:
        raise ValueError("compact staging requires scan_epochs (the "
                         "expander runs inside the scan body)")
    if compact is not None and dense_m is None:
        raise ValueError("compact staging requires the dense layout "
                         "(dense_m)")
    if node_cap is None or edge_cap is None:
        nc, ec = capacities_for(train_graphs, batch_size, dense_m=dense_m,
                                snug=snug)
        node_cap, edge_cap = node_cap or nc, edge_cap or ec
    if dense_m is not None:
        edge_cap = node_cap * dense_m
    pack_fn = None
    if compact is not None:
        from cgnn_tpu.data.compact import compact_pack_fn

        pack_fn = compact_pack_fn(compact)
    from cgnn_tpu.data.loader import prefetch_to_device

    def train_batches(rng):
        if buckets > 1:
            it = bucketed_batch_iterator(
                train_graphs, batch_size, buckets, shuffle=True, rng=rng,
                stats=pad_stats, dense_m=dense_m, snug=snug,
                edge_dtype=edge_dtype, pack_fn=pack_fn,
            )
        else:
            it = pad_stats.wrap(
                batch_iterator(
                    train_graphs, batch_size, node_cap, edge_cap,
                    shuffle=True, rng=rng, dense_m=dense_m, snug=snug,
                    edge_dtype=edge_dtype, pack_fn=pack_fn,
                )
            )
        # env-gated deterministic fault injection (NaN batches, loader
        # exceptions); returns `it` unwrapped when no plan is active
        return faultinject.poison_batches(it)

    def val_batches():
        # in_cap=0: eval has no backward, so skip transpose-slot packing
        if buckets > 1:
            return bucketed_batch_iterator(
                val_graphs, batch_size, buckets, dense_m=dense_m, in_cap=0,
                snug=snug, edge_dtype=edge_dtype, pack_fn=pack_fn,
            )
        return batch_iterator(
            val_graphs, batch_size, node_cap, edge_cap, dense_m=dense_m,
            in_cap=0, snug=snug, edge_dtype=edge_dtype, pack_fn=pack_fn,
        )

    telemetry = telemetry or Telemetry.disabled()
    # raw step BODIES (shared by the per-step jits below and the scan
    # driver, which stages its own in-scan tap); default steps compute
    # grad health in-graph at step-level telemetry — extra metric outputs
    # only, so the trajectory is unchanged
    base_train = train_step_fn or make_train_step(
        classification, grad_health=telemetry.step_level
    )
    if guard:
        # in-graph divergence guard INSIDE the jit/scan bodies (and
        # inside the telemetry tap below, so the stream sees skip flags)
        from cgnn_tpu.resilience.guard import guard_step

        base_train = guard_step(base_train)
    base_eval = eval_step_fn or make_eval_step(classification)
    train_step = jit_train_step(telemetry.wrap_train_body(base_train))
    eval_step = jax.jit(telemetry.wrap_eval_body(base_eval))
    best_key = best_metric or ("correct" if classification else "mae")
    best = -np.inf if classification else np.inf
    history = []
    rng = np.random.default_rng(seed)
    pad_stats = PaddingStats()

    def _with_profile(iterator, epoch):
        return profile_wrap(
            iterator,
            profile_steps if epoch == start_epoch else 0,
            profile_dir, log_fn,
        )

    driver: ScanEpochDriver | None = None
    if scan_epochs and (profile_steps or print_freq):
        log_fn(
            "scan_epochs: --profile and per-step prints are unavailable "
            "inside the whole-epoch scan (epoch-level metrics only)"
        )
    staging: dict = {}
    packed_lists: tuple | None = None
    if scan_epochs:
        # fold each epoch into one lax.scan dispatch per bucket shape over
        # the HBM-resident stacked batches (amortizes per-step dispatch
        # latency; see ScanEpochDriver and the fit docstring caveat)
        expand = None
        if compact is not None:
            from cgnn_tpu.data.compact import make_expander

            expand = make_expander(compact)
        t_pack = time.perf_counter()
        with telemetry.span("pack"):
            train_list = list(train_batches(rng))
            val_list = list(val_batches())
        staging["pack_s"] = round(time.perf_counter() - t_pack, 2)
        staged_bytes = staged_nbytes(train_list + val_list)
        staging["staged_mb"] = round(staged_bytes / 1e6, 1)
        staging["compact"] = compact is not None
        if check_device_resident_fit(staged_bytes, log_fn=log_fn):
            with telemetry.span("stage_scan_stacks",
                                staged_mb=staging["staged_mb"]):
                driver = ScanEpochDriver(
                    base_train,
                    base_eval,
                    train_list,
                    val_list,
                    rng,
                    expand=expand,
                    chunk_steps=chunk_steps,
                    telemetry=telemetry,
                    preempt=preempt,
                )
            telemetry.sample_hbm("post_staging")
            staging["stack_stage_dispatch_s"] = round(
                driver.timings["init_stack_stage_s"], 2
            )
        else:
            # LOUD fallback (check_device_resident_fit already logged the
            # numbers): keep the packed batches host-side and restage per
            # epoch instead of dying in an opaque XLA OOM mid-staging
            staging["fallback"] = "host_pack_once"
            scan_epochs = False
            device_resident = False
            packed_lists = (train_list, val_list)
            if expand is not None:
                # the per-step loop sees CompactBatches: expansion moves
                # into the jitted step bodies
                train_step = jit_train_step(
                    telemetry.wrap_train_body(
                        lambda s, b: base_train(s, expand(b))
                    )
                )
                eval_step = jax.jit(
                    telemetry.wrap_eval_body(
                        lambda s, b: base_eval(s, expand(b))
                    )
                )
    plan = (
        PackOncePlan(
            (lambda: packed_lists[0]) if packed_lists is not None
            else (lambda: train_batches(rng)),
            (lambda: packed_lists[1]) if packed_lists is not None
            else val_batches,
            rng,
            device_resident=device_resident,
        )
        if pack_once and driver is None
        else None
    )
    telemetry.observe_padding(pad_stats)
    preempted = False

    def finish_epoch(epoch, train_m, val_m, eval_truncated, t0):
        """Epoch bookkeeping that needs the fetched metrics (best
        tracking, history, logging, the metrics hook) — shared by the
        synchronous path and the deferred async-fetch path, which runs
        it one epoch late, after the NEXT epoch's dispatches are already
        in flight. Returns is_best."""
        nonlocal best
        if epoch == start_epoch:
            log_fn(pad_stats.summary())
        metric = val_m.get(best_key, np.nan)
        is_best = metric > best if classification else metric < best
        if eval_truncated:
            # preemption cut eval short: the metric covers a fraction of
            # the validation set — never let it repoint 'best'
            is_best = False
        if is_best:
            best = metric
        history.append({"epoch": epoch, "train": train_m, "val": val_m})
        epoch_s = time.perf_counter() - t0
        log_fn(
            f"Epoch {epoch}: train loss {train_m.get('loss', np.nan):.4f}"
            f"  val {best_key} {metric:.4f}{' *' if is_best else ''}"
            f"  ({epoch_s:.1f}s)"
        )
        # live-progress gauges + windowed epoch-time series: a mid-run
        # registry scrape (train.py --live-metrics / metrics_live.jsonl)
        # sees where the run is and how fast it is moving, instead of
        # waiting for the exit-time run_summary (host-side bookkeeping
        # only — the trajectory is untouched)
        telemetry.set_gauge("train_epoch", float(epoch))
        telemetry.set_gauge("train_loss_last",
                            float(train_m.get("loss", np.nan)))
        telemetry.set_gauge(f"val_{best_key}_last", float(metric))
        telemetry.set_gauge(f"val_{best_key}_best", float(best))
        telemetry.observe_value("epoch_time_s", epoch_s)
        if on_epoch_metrics is not None:
            on_epoch_metrics(epoch, train_m, val_m)
        return is_best

    # ISSUE 5 satellite: the epoch-pair sums fetch (SCAN_COST r5:
    # pair_fetch_s 224.9 ms of a 256 ms bench epoch) moves to a
    # background thread whenever the divergence monitor doesn't need the
    # sums before proceeding (--guard rollback). Full one-epoch-deep
    # overlap — epoch N's fetch runs while epoch N+1's scans dispatch —
    # additionally requires no epoch-end checkpoint consumer: the save
    # needs (state, metrics) together at the boundary, and the state is
    # donated into the next epoch's first scan the moment it dispatches.
    # With a consumer, the fetch thread still overlaps the next-epoch
    # schedule prebuild and is joined in-iteration (metrics bit-identical
    # either way, pinned by test).
    async_pair = driver is not None and monitor is None
    defer_pair = async_pair and on_epoch_end is None and preempt is None
    pending_prev: tuple | None = None  # (epoch, pending, eval_trunc, t0)

    for epoch in range(start_epoch, epochs):
        t0 = time.perf_counter()
        if driver is not None:
            with telemetry.span("epoch", epoch=epoch, driver="scan"):
                if async_pair:
                    state, pending = driver.run_epoch_pair(
                        state, first=epoch == start_epoch, async_fetch=True
                    )
                else:
                    state, train_m, val_m = driver.run_epoch_pair(
                        state, first=epoch == start_epoch
                    )
            aborted, eval_trunc = driver.aborted, driver.eval_truncated
            if defer_pair:
                if pending_prev is not None:
                    # epoch N-1's fetch ran while epoch N's dispatches
                    # were enqueued; resolve + bookkeep it now, with the
                    # device already streaming into epoch N
                    p_epoch, p_pending, p_trunc, p_t0 = pending_prev
                    tm, vm = p_pending.result()
                    finish_epoch(p_epoch, tm, vm, p_trunc, p_t0)
                    pending_prev = None
                if aborted:
                    # defensive only — defer_pair requires preempt=None,
                    # and the driver sets aborted solely from a preempt
                    # poll. Mirror the sync path: the partial epoch's
                    # metrics are DROPPED (never queued for bookkeeping)
                    save_preempted_mid_epoch(state, epoch, on_epoch_end,
                                             log_fn)
                    preempted = True
                    break
                pending_prev = (epoch, pending, eval_trunc, t0)
                faultinject.maybe_sigterm(epoch)
                continue
            if async_pair:
                train_m, val_m = pending.result()
            if aborted:
                save_preempted_mid_epoch(state, epoch, on_epoch_end, log_fn)
                preempted = True
                break
        else:
            if plan is not None:
                epoch_train, epoch_val = plan.epoch_iterators()
            else:
                epoch_train = train_batches(rng)
                epoch_val = val_batches()
            # device-resident batches need no staging; re-putting them
            # through the prefetch thread would only add overhead
            stage = (
                (lambda it: it) if device_resident
                else (lambda it: prefetch_to_device(it, telemetry=telemetry))
            )
            with telemetry.span("epoch", epoch=epoch, driver="per_step"):
                state, train_m = run_epoch(
                    train_step,
                    state,
                    _with_profile(stage(epoch_train), epoch),
                    train=True,
                    print_freq=print_freq,
                    epoch=epoch,
                    log_fn=log_fn,
                    telemetry=telemetry,
                )
            with telemetry.span("eval", epoch=epoch):
                _, val_m = run_epoch(
                    eval_step,
                    state,
                    stage(epoch_val),
                    train=False,
                    epoch=epoch,
                    log_fn=log_fn,
                    telemetry=telemetry,
                )
        is_best = finish_epoch(
            epoch, train_m, val_m,
            driver is not None and driver.eval_truncated, t0,
        )
        state, _, preempted = resilience_epoch_end(
            state, epoch, train_m, val_m, is_best, monitor=monitor,
            on_epoch_end=on_epoch_end, preempt=preempt, log_fn=log_fn,
        )
        if preempted:
            break
    if pending_prev is not None:
        # the deferred path's final epoch: nothing overlaps its fetch —
        # resolve and bookkeep it before reporting the run
        p_epoch, p_pending, p_trunc, p_t0 = pending_prev
        tm, vm = p_pending.result()
        finish_epoch(p_epoch, tm, vm, p_trunc, p_t0)
    out = {"best": best, "history": history}
    if preempted:
        out["preempted"] = True
    if staging:
        out["staging"] = staging
    return state, out


def evaluate(
    state: TrainState,
    graphs: Sequence[CrystalGraph],
    batch_size: int,
    node_cap: int,
    edge_cap: int,
    classification: bool = False,
    eval_step_fn: Callable | None = None,
    dense_m: int | None = None,
    snug: bool = False,
    edge_dtype=np.float32,
) -> dict:
    if dense_m is not None:
        edge_cap = node_cap * dense_m
    eval_step = jax.jit(eval_step_fn or make_eval_step(classification))
    _, metrics = run_epoch(
        eval_step,
        state,
        batch_iterator(graphs, batch_size, node_cap, edge_cap,
                       dense_m=dense_m, in_cap=0, snug=snug,
                       edge_dtype=edge_dtype),
        train=False,
    )
    return metrics
