"""Config dataclasses shared by train.py / predict.py (SURVEY.md §5).

The reference embeds its argparse namespace inside checkpoints so
``predict.py`` can rebuild the exact model; these dataclasses are that
contract, serialized into checkpoint metadata as a flat dict.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass
class ModelConfig:
    atom_fea_len: int = 64
    n_conv: int = 3
    h_fea_len: int = 128
    n_h: int = 1
    num_targets: int = 1
    classification: bool = False
    num_classes: int = 2
    dropout: float = 0.0
    dtype: str = "float32"  # 'float32' | 'bfloat16'
    aggregation: str | None = None  # None -> global default
    # config #3: per-task MLP stacks over the shared trunk instead of one
    # shared fc_out with T outputs (models/heads.py MultiTaskHead)
    multi_task_head: bool = False
    # dense edge-slot layout (data/graph.py pack_graphs dense_m): scatter-
    # free aggregation, ~2x faster train step on TPU; 0/None = flat COO.
    # Serialized so predict.py packs batches the way the model expects.
    dense_m: int = 0
    # fused BN1->gate->mask->sum epilogue: '' (off) | 'xla' | 'pallas'
    # (ops/fused_epilogue.py). Runtime choice with identical parameters —
    # checkpoints restore across settings — but serialized so predict
    # rebuilds what was trained.
    fused_epilogue: str = ""
    # WHOLE-conv fused kernel: '' (off) | 'xla' | 'pallas'
    # (ops/pallas_cgconv.py — gather+fc_full+BN1+gate+sum as one op).
    # Same parameter tree as the unfused path (checkpoints restore
    # across settings); cgconv_window is the caller-guaranteed neighbor
    # window bound (0 = whole node range, always correct), derived from
    # the dataset via pallas_cgconv.window_width — serialized together
    # so predict rebuilds what was trained.
    cgconv_impl: str = ""
    cgconv_window: int = 0

    def to_meta(self) -> dict:
        return dataclasses.asdict(self) | {
            "aggregation": self.aggregation or "__none__"
        }

    @classmethod
    def from_meta(cls, meta: dict) -> "ModelConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in meta.items() if k in fields}
        kw["classification"] = bool(kw.get("classification", 0))
        kw["multi_task_head"] = bool(kw.get("multi_task_head", 0))
        kw["dense_m"] = int(kw.get("dense_m", 0))
        kw["fused_epilogue"] = str(kw.get("fused_epilogue", "") or "")
        kw["cgconv_impl"] = str(kw.get("cgconv_impl", "") or "")
        kw["cgconv_window"] = int(kw.get("cgconv_window", 0))
        if kw.get("aggregation") in ("__none__", None):
            kw["aggregation"] = None
        return cls(**kw)

    def for_arbitrary_inputs(self) -> "ModelConfig":
        """This config with data-derived bounds widened to always-correct
        settings — the ONE place the invariant lives for inference entry
        points (predict.py, serve load_server, any future export path).

        The serialized ``cgconv_window`` covers the TRAINING set only;
        arbitrary inference inputs can exceed it, and an undersized
        bound silently zeroes out-of-window neighbors in the fused
        conv's in-kernel gather (ops/pallas_cgconv.py contract).
        ``cgconv_window=0`` = full-range gather, always correct."""
        if not self.cgconv_impl or self.cgconv_window == 0:
            return self
        return dataclasses.replace(self, cgconv_window=0)

    def build(self, head=None, edge_axis_name: str | None = None):
        """``edge_axis_name`` activates edge-sharded graph parallelism
        (psum over that mesh axis inside every conv). It is a runtime
        parallelism choice, not model identity — deliberately NOT part of
        ``to_meta()``, so checkpoints restore as plain single-device models
        with identical parameters."""
        from cgnn_tpu.models import CrystalGraphConvNet

        if head is None and self.multi_task_head and not self.classification:
            from cgnn_tpu.models.heads import MultiTaskHead

            head = MultiTaskHead(
                num_tasks=self.num_targets,
                h_fea_len=self.h_fea_len,
                n_h=self.n_h,
                dtype=jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32,
            )
        import jax

        fused = self.fused_epilogue or None
        if fused == "pallas" and jax.default_backend() != "tpu":
            # the Pallas kernels lower only on TPU; 'xla' is numerically
            # identical, so a TPU-trained checkpoint stays loadable for
            # CPU prediction/fine-tuning
            fused = "xla"
        cgconv = self.cgconv_impl or None
        if cgconv == "pallas" and jax.default_backend() != "tpu":
            cgconv = "xla"  # same backend rule as fused_epilogue
        return CrystalGraphConvNet(
            atom_fea_len=self.atom_fea_len,
            n_conv=self.n_conv,
            h_fea_len=self.h_fea_len,
            n_h=self.n_h,
            num_targets=self.num_targets,
            classification=self.classification,
            num_classes=self.num_classes,
            dropout_rate=self.dropout,
            dtype=jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32,
            aggregation_impl=self.aggregation,
            head=head,
            edge_axis_name=edge_axis_name,
            dense_m=self.dense_m or None,
            fused_epilogue=fused,
            cgconv_impl=cgconv,
            cgconv_window=self.cgconv_window,
        )


def build_model(model_cfg: "ModelConfig", data_cfg: "DataConfig",
                task: str = "regression",
                edge_axis_name: str | None = None):
    """Build the model for a task; the force task needs the edge featurization
    hyperparameters in-model (distances are recomputed differentiably from
    positions — models/forcefield.py)."""
    if task == "force":
        if edge_axis_name is not None:
            raise NotImplementedError(
                "graph sharding is not supported for the force task"
            )
        from cgnn_tpu.models.forcefield import ForceFieldCGCNN

        return ForceFieldCGCNN(
            atom_fea_len=model_cfg.atom_fea_len,
            n_conv=model_cfg.n_conv,
            h_fea_len=model_cfg.h_fea_len,
            dmin=data_cfg.dmin,
            dmax=data_cfg.radius,
            step=data_cfg.step,
            dtype=jnp.bfloat16 if model_cfg.dtype == "bfloat16" else jnp.float32,
            aggregation_impl=model_cfg.aggregation,
            dense_m=model_cfg.dense_m or None,
        )
    return model_cfg.build(edge_axis_name=edge_axis_name)


@dataclasses.dataclass
class DataConfig:
    radius: float = 8.0
    max_num_nbr: int = 12
    dmin: float = 0.0
    step: float = 0.2

    def to_meta(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_meta(cls, meta: dict) -> "DataConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in meta.items() if k in fields})

    def featurize_config(self):
        from cgnn_tpu.data.dataset import FeaturizeConfig

        return FeaturizeConfig(
            radius=self.radius,
            max_num_nbr=self.max_num_nbr,
            dmin=self.dmin,
            step=self.step,
        )
