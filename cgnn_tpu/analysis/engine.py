"""The graftcheck engine: file walking, disable comments, findings.

Disable-comment policy (INVARIANTS.md):

    x = jax.device_get(t)  # graftcheck: disable=GC-ALIAS -- audited:
                           # consumed read-only before the next dispatch

- ``disable=RULE[,RULE2]`` names the silenced rule(s);
- everything after ``--`` is the REQUIRED justification — a disable
  without one (or naming an unknown rule) is itself a finding
  (GC-DISABLE): the escape hatch must say why, or the catalog rots;
- a trailing comment covers its own (possibly multi-line) statement; a
  standalone comment line covers the next code line.

Stdlib-only: ast + tokenize, no jax — the CI static-analysis job runs
on a bare interpreter.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize

from cgnn_tpu.analysis.rules import RULES, check_module

_DISABLE_RE = re.compile(
    r"#\s*graftcheck:\s*disable=([A-Za-z0-9_,\- ]+?)"
    r"(?:\s*--\s*(.*))?$"
)

# scanned by default: the package, the scripts, and the root
# entrypoints. tests/ is excluded (test code stubs threads and fakes
# locks on purpose; the fixture corpus under tests/analysis_fixtures is
# scanned explicitly by its own tests), and __graft_entry__.py is the
# frozen seed harness the graft driver keys on byte-for-byte.
_DEFAULT_DIRS = ("cgnn_tpu", "scripts")
_DEFAULT_ROOT_GLOB = (".py",)
_EXCLUDE_NAMES = {"__graft_entry__.py"}
_EXCLUDE_DIRS = {"__pycache__", "tests", ".git"}


@dataclasses.dataclass
class Finding:
    """One rule violation at a file:line."""

    rule: str
    path: str
    line: int
    message: str

    def format(self, verbose: bool = True) -> str:
        head = f"{self.path}:{self.line}: {self.rule}"
        if not verbose:
            return head
        return f"{head}: {self.message}"


@dataclasses.dataclass
class _Disable:
    rules: tuple
    justified: bool
    line: int


def _parse_disables(source: str):
    """-> ({covered line -> [rules]}, [bad-disable Finding stubs]).

    Uses tokenize so strings containing '# graftcheck:' don't count.
    """
    covered: dict[int, set] = {}
    bad: list[tuple[int, str]] = []
    code_lines = set()
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return covered, bad
    comments = []
    for tok in tokens:
        if tok.type == tokenize.COMMENT:
            m = _DISABLE_RE.search(tok.string)
            if m:
                comments.append((tok.start[0],
                                 tok.start[1] == 0 or _only_ws_before(
                                     source, tok.start),
                                 m))
        elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                              tokenize.INDENT, tokenize.DEDENT,
                              tokenize.ENCODING, tokenize.ENDMARKER):
            code_lines.add(tok.start[0])
    for lineno, standalone, m in comments:
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        justification = (m.group(2) or "").strip()
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            bad.append((lineno,
                        f"disable names unknown rule(s) {unknown} "
                        f"(known: {', '.join(sorted(RULES))})"))
            continue
        if not justification:
            bad.append((lineno,
                        "disable without a justification string: write "
                        "'# graftcheck: disable=RULE -- why this site "
                        "is safe' (INVARIANTS.md policy)"))
            continue
        target = lineno
        if standalone and lineno not in code_lines:
            # standalone comment: covers the next code line
            nxt = [n for n in code_lines if n > lineno]
            if nxt:
                target = min(nxt)
        covered.setdefault(target, set()).update(rules)
        # a trailing comment on line N of a multi-line statement covers
        # the statement it rides on; the node-range check in check_file
        # handles that by testing every line of the node's span
        covered.setdefault(lineno, set()).update(rules)
    return covered, bad


def _only_ws_before(source: str, start) -> bool:
    line = source.splitlines()[start[0] - 1]
    return line[: start[1]].strip() == ""


def check_file(path: str, source: str | None = None,
               rel_to: str | None = None) -> list[Finding]:
    """Run every rule over one file; disables already applied."""
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    display = os.path.relpath(path, rel_to) if rel_to else path
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        # its own rule id, NOT GC-DISABLE: the CI "every rule has
        # corpus teeth" check matches on rule ids, and a syntax-error
        # fixture must not vacuously satisfy the disable-policy rule
        return [Finding("GC-PARSE", display, e.lineno or 0,
                        f"file does not parse: {e.msg} — graftcheck "
                        f"cannot vouch for invariants it cannot see")]
    covered, bad = _parse_disables(source)
    findings = [
        Finding("GC-DISABLE", display, lineno, msg) for lineno, msg in bad
    ]
    for raw in check_module(tree, path):
        span = range(raw.line, raw.end_line + 1)
        if any(raw.rule in covered.get(n, ()) for n in span):
            continue
        findings.append(Finding(raw.rule, display, raw.line, raw.message))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def default_targets(root: str) -> list[str]:
    """The repo-wide scan set (module docstring on exclusions)."""
    targets = []
    for entry in sorted(os.listdir(root)):
        full = os.path.join(root, entry)
        if (os.path.isfile(full) and entry.endswith(_DEFAULT_ROOT_GLOB)
                and entry not in _EXCLUDE_NAMES):
            targets.append(full)
    for d in _DEFAULT_DIRS:
        base = os.path.join(root, d)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [n for n in sorted(dirnames)
                           if n not in _EXCLUDE_DIRS]
            for name in sorted(filenames):
                if name.endswith(".py") and name not in _EXCLUDE_NAMES:
                    targets.append(os.path.join(dirpath, name))
    return targets


def check_paths(paths, rel_to: str | None = None) -> list[Finding]:
    """Run the full rule set over files and/or directories."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [n for n in sorted(dirnames)
                               if n not in _EXCLUDE_DIRS]
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(filenames)
                             if n.endswith(".py"))
        else:
            files.append(p)
    findings = []
    for f in files:
        findings.extend(check_file(f, rel_to=rel_to))
    return findings
