"""Compiled-program auditor: IR-level invariants + the roofline ledger.

graftcheck (rules.py) proves SOURCE-level invariants; the repo's
costliest incidents live a layer lower, in what XLA actually compiles:
donation silently not applied (the buffer-copy-per-step failure mode),
f64 creep doubling HBM traffic, stray host callbacks serializing the
device stream, and near-duplicate programs compiled per rung from a
leaked Python scalar. This module lowers the repo's REAL entry
programs — the train step (plain, guard-wrapped, telemetry-tapped,
dense, DP/edge-sharded where the backend allows), the serving/predict
program for every (rung, staging form) in the warm shape ladder, and
the compact expander — via ``jax.jit(...).lower()`` on abstract args
(no device dispatch), then statically audits the StableHLO/compiled
artifacts:

- **GA-DONATION** — input-output aliasing actually present for every
  ``donate_argnums`` leaf (``tf.aliasing_output`` in the StableHLO,
  ``alias_size_in_bytes`` in the compiled memory stats);
- **GA-F64** — no f64 values anywhere in any module;
- **GA-HOSTCALL** — the only callback custom-call in any program is
  the sanctioned observe/stream tap, and only in the telemetry=step
  program; every other custom-call target must be allowlisted;
- **GA-IDENT** — the ladder produces exactly programs x rungs x forms
  distinct programs PER ENGINE (the mesh-sharded predict programs are
  registered alongside the single-device ladder), and no two differ
  only in burned-in constants (the Python-scalar-leakage recompile
  shape);
- **GA-SHARD** — a mesh-sharded program's per-device argument bytes
  stay within the replicated-params + batch/N model, so a batch
  silently replicated to every device (the classic NamedSharding
  mistake) blocks CI;
- the **roofline ledger** — per-program FLOPs, memory bytes, and peak
  temp memory from XLA ``cost_analysis``/``memory_analysis``, with
  arithmetic intensity, written to ``AUDIT_LEDGER.json`` and gated in
  CI as budgets (``diff_ledgers``: dropped key or >20% regression of a
  lower-is-better key fails, mirroring scripts/bench_regress.py).

``graftaudit.py`` is the CLI; tests/test_program_audit.py holds the
broken-program fixtures (donation deliberately broken, an f64 sneaked
in, a pure_callback added) that each check must catch.

jax imports are LAZY (function-local): ``diff_ledgers`` and the check
catalog stay importable on a bare interpreter, like the rest of
``cgnn_tpu.analysis``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from typing import Any, Callable

# check id -> one-line description (the --list-checks output;
# INVARIANTS.md "IR-level invariants" carries the full write-ups)
CHECKS = {
    "GA-DONATION": (
        "donation declared but not applied: a donate_argnums leaf "
        "without input-output aliasing in the lowered/compiled program "
        "means XLA silently keeps BOTH buffers — the train step then "
        "pays a full parameter copy per step (the failure mode the "
        "PR-2 checkpoint incident proved donation is live on, "
        "CHANGES.md PR 2)."
    ),
    "GA-F64": (
        "f64 value in a compiled program: accidental float64 promotion "
        "doubles HBM bytes on the exact gather/scatter paths that hold "
        "MFU at ~3% (ROADMAP item 2) and falls off the TPU fast path "
        "entirely; the dtype policy is f32/bf16 everywhere."
    ),
    "GA-HOSTCALL": (
        "unsanctioned custom-call/callback in a compiled program: the "
        "ONE audited host callback is the observe/stream telemetry tap "
        "(unordered, muted at warmup), present only in the "
        "telemetry=step program (CHANGES.md PR 1); anything else "
        "serializes the device stream against the host."
    ),
    "GA-IDENT": (
        "program-identity drift: the warm ladder must produce exactly "
        "programs x rungs x forms distinct programs (CHANGES.md PR 3); "
        "two programs differing ONLY in burned-in constants are the "
        "Python-scalar-leakage shape — every new scalar value "
        "recompiles at runtime."
    ),
    "GA-LOWER": (
        "a registered entry program failed to lower for an unexpected "
        "reason (known backend gaps — e.g. this container's jax "
        "missing shard_map — are recorded as skips, not findings)."
    ),
    "GA-SHARD": (
        "a mesh-sharded program's per-device argument bytes exceed the "
        "replicated-params + batch/N model: the classic NamedSharding "
        "mistake is staging the batch WITHOUT the batch-axis sharding "
        "(or with P()), which silently replicates every staged byte to "
        "every device — N x the H2D traffic and HBM of the sharded "
        "layout with identical outputs, exactly the cost the mesh "
        "engine (parallel/executor.py, ISSUE 10) exists to avoid. The "
        "compiled executable's per-device argument_size_in_bytes is "
        "budgeted against the analytic sharded model so that mistake "
        "blocks CI."
    ),
    "GA-ROOFLINE": (
        "a byte-budgeted program's cost-analysis bytes exceed its "
        "analytic HBM model: the whole-conv fused kernel "
        "(ops/pallas_cgconv.py) is built on reading its inputs and "
        "writing ONLY the [N, F] aggregate — a later change that "
        "silently rematerializes v_j/z (an [N, M, *] intermediate) in "
        "HBM reintroduces exactly the staging round-trips the kernel "
        "exists to remove (PERF.md §6b's failure mode), and this check "
        "blocks CI on it."
    ),
}

# lower-is-better ledger keys gated by diff_ledgers (the budget)
LEDGER_GATE_KEYS = ("bytes", "peak_temp_bytes", "bytes_per_flop")

# custom-call targets that are XLA plumbing, not host calls
_ALLOWED_CUSTOM_CALLS = {
    "Sharding",
    "SPMDFullToShardShape",
    "SPMDShardToFullShape",
    "annotate_device_placement",
    # Mosaic-compiled Pallas kernels (ops/pallas_cgconv.py and friends)
    # lower to this target on TPU: a DEVICE kernel, not a host call —
    # GA-HOSTCALL polices host-callback surfaces, and GA-ROOFLINE is
    # the check that owns what these kernels do to HBM
    "tpu_custom_call",
}

_CUSTOM_CALL_RE = re.compile(r"custom_call\s+@([\w.$]+)")
_CONST_RE = re.compile(r"dense<[^>]*>")
_BACKEND_CONFIG_RE = re.compile(r'backend_config\s*=\s*"[^"]*"')


@dataclasses.dataclass
class AuditFinding:
    """One IR-level violation in one entry program."""

    check: str
    program: str
    message: str

    def format(self) -> str:
        return f"{self.program}: {self.check}: {self.message}"


@dataclasses.dataclass
class AuditConfig:
    """Deterministic synthetic setup the entry programs lower against.

    Small on purpose (the audit runs per-PR on CPU): the invariants
    checked — aliasing, dtypes, custom-call targets, program identity —
    are shape-independent, and the roofline ledger only needs to be
    SELF-consistent between rounds, which fixed shapes + a fixed seed
    guarantee."""

    n_graphs: int = 64
    batch_size: int = 16
    rungs: int = 3
    dense_m: int = 8
    seed: int = 0
    atom_fea_len: int = 16
    n_conv: int = 2
    h_fea_len: int = 32

    def to_meta(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Program:
    """One lowered (or loudly skipped) entry program."""

    name: str
    jitted: Any = None
    args: tuple = ()
    donated_leaves: int = 0  # expected aliased input leaves (0 = none)
    callbacks: int = 0  # expected sanctioned callback custom-calls
    skip: str | None = None  # reason this backend cannot lower it
    lowered: Any = None
    text: str = ""
    # analytic HBM byte budget (0 = ungated): compiled cost-analysis
    # bytes above budget * GA-ROOFLINE's slack is a finding
    byte_budget: int = 0
    # analytic PER-DEVICE argument-byte budget (0 = ungated): the
    # GA-SHARD gate for mesh-sharded programs — replicated params +
    # this device's 1/N batch slice; a silently replicated batch blows
    # straight through it
    arg_byte_budget: int = 0


def abstract_avals(tree):
    """Map every leaf to a ``jax.ShapeDtypeStruct`` (PRNG-key dtypes
    preserved): the no-device-dispatch argument form for ``lower``."""
    import jax
    import numpy as np

    def aval(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        arr = np.asarray(x)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    return jax.tree_util.tree_map(aval, tree)


def lower_train_program(state, batch, *, body: Callable | None = None,
                        guard: bool = False, telemetry=None):
    """Lower a train-step body through the ONE canonical path.

    ``train/step.jit_train_step`` declares the donation; this helper
    adds the standard wrappers in the order train/loop.py applies them
    (guard inside, telemetry tap outside) and lowers on abstract avals.
    Used by the audit registry AND scripts/hlo_dump.py, so there is
    exactly one jit/lower plumbing for train programs."""
    from cgnn_tpu.train.step import jit_train_step, make_train_step

    body = body or make_train_step()
    if guard:
        from cgnn_tpu.resilience.guard import guard_step

        body = guard_step(body)
    if telemetry is not None:
        body = telemetry.wrap_train_body(body)
    return jit_train_step(body).lower(
        abstract_avals(state), abstract_avals(batch)
    )


# ---- the entry-program registry --------------------------------------


def build_entry_programs(config: AuditConfig | None = None,
                         telemetry_dir: str | None = None):
    """-> (programs, meta): the repo's real entry programs, lowered.

    Known backend gaps become ``skip`` records (listed in the ledger
    meta, never silently absent): the dense-layout train step needs a
    jax whose ``linear_call`` differentiates (this container's 0.4.37
    does not; CI's does), and the DP/edge-sharded steps need
    ``jax.shard_map`` plus >= 2 devices. Everything else must lower —
    an unexpected failure is a GA-LOWER finding, not a skip."""
    import tempfile

    import jax
    import numpy as np

    from cgnn_tpu.data.compact import CompactSpec, make_expander
    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.serve.shapes import plan_shape_set
    from cgnn_tpu.train import (
        Normalizer,
        create_train_state,
        make_optimizer,
    )
    from cgnn_tpu.train.step import make_predict_step, make_train_step

    cfg = config or AuditConfig()
    m = cfg.dense_m
    fcfg = FeaturizeConfig(radius=6.0, max_num_nbr=m)
    # keep_geometry: the ISSUE-11 raw-wire spec calibrates its image
    # caps from the calibration lattices
    graphs = load_synthetic_mp(cfg.n_graphs, fcfg, seed=cfg.seed,
                               keep_geometry=True)
    targets = np.stack([g.target for g in graphs])
    spec = CompactSpec.build(graphs, fcfg.gdf(), dense_m=m)
    from cgnn_tpu.data.rawbatch import plan_raw_spec

    raw_spec = plan_raw_spec(graphs, fcfg.gdf(), fcfg.radius, m)
    ladder = plan_shape_set(graphs, cfg.batch_size, rungs=cfg.rungs,
                            dense_m=m, compact=spec, raw=raw_spec)

    def make_state(model, example):
        return create_train_state(
            model, example, make_optimizer(),
            Normalizer.fit(targets), rng=jax.random.key(cfg.seed),
        )

    # COO layout: the train programs every backend can lower
    model_coo = CrystalGraphConvNet(
        atom_fea_len=cfg.atom_fea_len, n_conv=cfg.n_conv,
        h_fea_len=cfg.h_fea_len,
    )
    nc, ec = capacities_for(graphs, cfg.batch_size, snug=True)
    coo_batch = next(batch_iterator(graphs, cfg.batch_size, nc, ec,
                                    snug=True))
    state_coo = make_state(model_coo, coo_batch)
    n_leaves = len(jax.tree_util.tree_leaves(abstract_avals(state_coo)))
    coo_av = abstract_avals(coo_batch)
    state_coo_av = abstract_avals(state_coo)

    # dense layout: the flagship/serving layout (predict + dense train)
    model_dense = CrystalGraphConvNet(
        atom_fea_len=cfg.atom_fea_len, n_conv=cfg.n_conv,
        h_fea_len=cfg.h_fea_len, dense_m=m,
    )
    ncd, ecd = capacities_for(graphs, cfg.batch_size, dense_m=m, snug=True)
    dense_batch = next(batch_iterator(graphs, cfg.batch_size, ncd, ecd,
                                      dense_m=m, snug=True))
    state_dense = make_state(model_dense, dense_batch)
    state_dense_av = abstract_avals(state_dense)

    from cgnn_tpu.train.step import jit_train_step

    programs: list[Program] = []

    def add(name, jitted, args, donated=0, callbacks=0):
        programs.append(Program(name=name, jitted=jitted, args=args,
                                donated_leaves=donated,
                                callbacks=callbacks))

    def add_skip(name, reason):
        programs.append(Program(name=name, skip=reason))

    # -- train step: plain / guard-wrapped / telemetry-tapped (COO) --
    add("train/coo", jit_train_step(make_train_step()),
        (state_coo_av, coo_av), donated=n_leaves)
    from cgnn_tpu.resilience.guard import guard_step

    add("train/coo+guard", jit_train_step(guard_step(make_train_step())),
        (state_coo_av, coo_av), donated=n_leaves)
    # telemetry=step: the ONE program allowed a host callback (the
    # observe/stream tap), wrapped exactly as train/loop.py wraps it
    # (guard inside, tap outside, grad health on at step level)
    from cgnn_tpu.observe.telemetry import Telemetry

    tel = Telemetry(level="step",
                    log_dir=telemetry_dir or tempfile.mkdtemp(
                        prefix="graftaudit-tap-"))
    try:
        tap_body = tel.wrap_train_body(
            guard_step(make_train_step(grad_health=True))
        )
        add("train/coo+tap@step", jit_train_step(tap_body),
            (state_coo_av, coo_av), donated=n_leaves, callbacks=1)
    finally:
        tel.close()

    # -- train step: dense layout (the bench/serving layout) --
    add("train/dense", jit_train_step(make_train_step()),
        (state_dense_av, abstract_avals(dense_batch)), donated=n_leaves)

    # -- train step: DP / edge-sharded (where the backend allows) --
    shard_gap = None
    if len(jax.devices()) < 2:
        shard_gap = (f"needs >= 2 devices, have {len(jax.devices())} "
                     f"(CI sets --xla_force_host_platform_device_count)")
    elif not hasattr(jax, "shard_map"):
        # the parallel/compat.py shim RUNS these bodies on legacy
        # experimental shard_map, but legacy lowering drops the
        # donation aliasing from the module text (jax.buffer_donor
        # without tf.aliasing_output) — auditing it here would flag a
        # version artifact, not a repo bug; CI's jax audits the real
        # thing
        shard_gap = ("legacy experimental shard_map (pre-jax.shard_map) "
                     "does not propagate donation aliasing into the "
                     "lowered module; CI audits these")
    if shard_gap is None:
        from cgnn_tpu.parallel.data_parallel import (
            make_parallel_train_step,
            stack_batches,
        )
        from cgnn_tpu.parallel.edge_parallel import (
            make_edge_parallel_train_step,
            pad_edges_divisible,
        )
        from cgnn_tpu.parallel.mesh import make_mesh

        n_dev = len(jax.devices())
        mesh = make_mesh(n_dev)
        stacked_av = abstract_avals(stack_batches([coo_batch] * n_dev))
        add("train/dp", make_parallel_train_step(mesh).jitted,
            (state_coo_av, stacked_av), donated=n_leaves)

        from jax.sharding import Mesh

        gmesh = Mesh(np.array(jax.devices()), ("graph",))
        model_gp = CrystalGraphConvNet(
            atom_fea_len=cfg.atom_fea_len, n_conv=cfg.n_conv,
            h_fea_len=cfg.h_fea_len, edge_axis_name="graph",
        )
        state_gp_av = abstract_avals(
            state_coo.replace(apply_fn=model_gp.apply)
        )
        edge_av = abstract_avals(pad_edges_divisible(coo_batch, n_dev))
        add("train/edge", make_edge_parallel_train_step(gmesh),
            (state_gp_av, edge_av), donated=n_leaves)
    else:
        add_skip("train/dp", shard_gap)
        add_skip("train/edge", shard_gap)

    # -- the whole-conv fused forward (ops/pallas_cgconv.py; ROADMAP
    # item 2): byte-budgeted against its analytic one-round-trip model
    # so a silent [N, M, *] rematerialization blocks CI (GA-ROOFLINE).
    # The structured 'xla' twin lowers on every backend; the Pallas
    # kernels lower only on TPU (recorded as a skip elsewhere).
    from cgnn_tpu.ops.pallas_cgconv import (
        fused_cgconv_eval,
        fused_conv_hbm_bytes,
    )

    fdim = cfg.atom_fea_len
    gdim = graphs[0].edge_fea.shape[1]
    byte_model = fused_conv_hbm_bytes(ncd, m, gdim, fdim)
    # eval mode = ONE apply pass: budget is one read set + the write
    eval_budget = int(byte_model["reads_per_pass"]
                      + byte_model["write_bytes"])

    def _fused_fwd_fn(impl):
        def f(nodes, edges, kernel, bias, scale, bn_bias, mean, var,
              neighbors, emask):
            return fused_cgconv_eval(
                nodes, edges, kernel, bias, scale, bn_bias, neighbors,
                emask, mean, var, impl=impl, window=0,
            )

        return jax.jit(f)

    c2 = 2 * fdim
    fused_avals = (
        jax.ShapeDtypeStruct((ncd, fdim), np.float32),       # nodes
        jax.ShapeDtypeStruct((ncd, m, gdim), np.float32),    # edges
        jax.ShapeDtypeStruct((c2 + gdim, c2), np.float32),   # kernel
        jax.ShapeDtypeStruct((c2,), np.float32),             # bias
        jax.ShapeDtypeStruct((c2,), np.float32),             # scale
        jax.ShapeDtypeStruct((c2,), np.float32),             # bn_bias
        jax.ShapeDtypeStruct((c2,), np.float32),             # mean
        jax.ShapeDtypeStruct((c2,), np.float32),             # var
        jax.ShapeDtypeStruct((ncd * m,), np.int32),          # neighbors
        jax.ShapeDtypeStruct((ncd, m), np.float32),          # edge mask
    )
    # the structured twin is NOT absolute-budgeted (its jnp ops carry
    # logical [N, M, *] intermediates whose cost-analysis bytes XLA may
    # or may not fuse away, backend-dependent) — its ledger row is
    # budget-gated RELATIVELY by diff_ledgers (>20% bytes regression
    # fails CI), which is what catches a rematerialization creeping
    # into the structured path on the CPU CI leg.
    programs.append(Program(
        name="conv/fused_xla_fwd", jitted=_fused_fwd_fn("xla"),
        args=fused_avals,
    ))
    if jax.default_backend() == "tpu":
        programs.append(Program(
            name="conv/fused_pallas_fwd", jitted=_fused_fwd_fn("pallas"),
            args=fused_avals, byte_budget=eval_budget,
        ))
    else:
        add_skip("conv/fused_pallas_fwd",
                 "Pallas TPU kernels lower only on a tpu backend "
                 "(config.py backend rule); CI's TPU leg audits it")

    # -- predict: every (rung, staging form) in the warm ladder — the
    # forms dimension now includes 'raw' (ISSUE 11: the in-program
    # neighbor-search + featurize program per rung) --
    pstep = jax.jit(make_predict_step(ladder.expander(),
                                      ladder.raw_expander()))
    batch_avals = ladder.abstract_batches(graphs[0])
    for (rung, form), batch_av in sorted(batch_avals.items()):
        add(f"predict/rung{rung}/{form}", pstep,
            (state_dense_av, batch_av))

    # -- predict: the mesh-sharded engine dimension (ISSUE 10) — the
    # same rungs x forms through the MeshExecutor single-dispatch
    # program, GA-SHARD-budgeted so a silently replicated batch (the
    # classic NamedSharding mistake) blocks CI. GA-IDENT's expected
    # predict count accounts for this engine dimension below.
    mesh_devices = 0
    if len(jax.devices()) >= 2:
        from cgnn_tpu.parallel.executor import MeshExecutor

        executor = MeshExecutor(jax.devices())
        mesh_devices = len(executor)
        mesh_pred = executor.shard_predict(
            make_predict_step(ladder.expander(), ladder.raw_expander()))

        def _aval_bytes(tree) -> int:
            total = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                try:
                    item = np.dtype(leaf.dtype).itemsize
                except TypeError:
                    item = 8  # PRNG key leaves (uint32[2] key data)
                total += int(np.prod(leaf.shape, dtype=np.int64)) * item
            return total

        state_bytes = _aval_bytes(state_dense_av)
        for (rung, form), batch_av in sorted(batch_avals.items()):
            stacked_av = executor.abstract_stacked(batch_av)
            # the sharded model: every device holds the full replicated
            # state plus exactly its 1/N slice of the stacked batch
            # (XLA drops unused state args, so this is an upper bound
            # on the CORRECT layout and far below a replicated batch)
            budget = state_bytes + _aval_bytes(stacked_av) // mesh_devices
            programs.append(Program(
                name=f"predict/mesh/rung{rung}/{form}",
                jitted=mesh_pred, args=(state_dense_av, stacked_av),
                arg_byte_budget=budget,
            ))
    else:
        add_skip("predict/mesh",
                 "the mesh-sharded predict program needs >= 2 devices "
                 "(CI sets --xla_force_host_platform_device_count)")
    # -- the compact expander as its own program (the fused on-device
    # featurize the serving fast path rides on) --
    add("expander/rung0", jax.jit(make_expander(spec)),
        (batch_avals[(0, "compact")],))

    # -- the in-program neighbor search as its own program, GA-ROOFLINE
    # budgeted against its analytic candidate-matrix byte model: the
    # [S, S*K] dense candidate pass is the intended working set, and a
    # rematerialized per-candidate FEATURE tensor (the G-fold blowup the
    # budget exists to catch) blows straight through the slack --
    from cgnn_tpu.ops.neighbor_search import (
        neighbor_search,
        neighbor_search_hbm_bytes,
    )

    raw_av0 = batch_avals[(0, "raw")]
    g_cap0 = raw_av0.targets.shape[0]

    def _search_fn(frac, lats, amask):
        return neighbor_search(frac, lats, amask, raw_spec)

    search_budget = neighbor_search_hbm_bytes(
        g_cap0, raw_spec.snode_cap, raw_spec.n_images, raw_spec.dense_m
    )["budget_bytes"]
    programs.append(Program(
        name="ops/neighbor_search/rung0", jitted=jax.jit(_search_fn),
        args=(raw_av0.frac, raw_av0.lattices, raw_av0.atom_mask),
        byte_budget=search_budget,
    ))

    meta = {
        "config": cfg.to_meta(),
        "ladder": ladder.to_meta(),
        # the engine dimension counts (GA-IDENT): the single-device
        # ladder programs plus, where the backend has the devices, the
        # mesh-sharded twin of every (rung, form)
        "predict_programs_expected": len(batch_avals) * (
            2 if mesh_devices else 1),
        "mesh_devices": mesh_devices,
        "state_leaves": n_leaves,
        # the fused conv's analytic HBM model (ops/pallas_cgconv.py
        # fused_conv_hbm_bytes): the GA-ROOFLINE budget for the Pallas
        # program and the documented target for the structured twin's
        # relative gate
        "fused_conv_byte_model": {
            **byte_model, "eval_budget_bytes": eval_budget,
            "shape": {"n": ncd, "m": m, "g": gdim, "f": fdim},
        },
        # the ISSUE-11 neighbor-search byte model (GA-ROOFLINE target)
        "neighbor_search_byte_model": neighbor_search_hbm_bytes(
            g_cap0, raw_spec.snode_cap, raw_spec.n_images,
            raw_spec.dense_m,
        ),
        "raw_spec": raw_spec.to_meta(),
    }
    return programs, meta


def lower_programs(programs: list[Program]) -> list[AuditFinding]:
    """Fill ``lowered``/``text`` per program; known backend gaps become
    skips, anything else a GA-LOWER finding."""
    findings = []
    for p in programs:
        if p.skip is not None:
            continue
        try:
            p.lowered = p.jitted.lower(*p.args)
            p.text = p.lowered.as_text()
        except NotImplementedError as e:
            # the in-container jax 0.4.37 dense-layout linear_call gap
            # (CHANGES.md PR 1: the cause of the 43 seed failures) —
            # recorded, surfaced in the ledger meta, lowered in CI
            p.skip = f"backend cannot lower: {e}"
        except Exception as e:  # noqa: BLE001 - findings, not crashes
            findings.append(AuditFinding(
                "GA-LOWER", p.name,
                f"unexpected lowering failure: {type(e).__name__}: {e}",
            ))
            p.skip = f"lowering failed: {type(e).__name__}"
    return findings


# ---- per-program text checks -----------------------------------------


def _has_f64(text: str) -> bool:
    # element types read 'tensor<4xf64>' / 'tensor<f64>'; free the
    # 'xf64' form so a word boundary exists, then match the dtype token
    return re.search(r"\bf64\b", text.replace("xf64", " f64")) is not None


def _custom_calls(text: str) -> list[str]:
    return _CUSTOM_CALL_RE.findall(text)


def _is_callback(target: str) -> bool:
    return "callback" in target.lower()


def check_donation(p: Program) -> list[AuditFinding]:
    if p.donated_leaves <= 0:
        return []
    out = []
    aliased = p.text.count("tf.aliasing_output")
    donors = p.text.count("jax.buffer_donor")
    if aliased < p.donated_leaves:
        out.append(AuditFinding(
            "GA-DONATION", p.name,
            f"only {aliased} of {p.donated_leaves} donated input leaves "
            f"carry tf.aliasing_output in the lowered module — the "
            f"un-aliased leaves get a fresh output buffer plus a copy "
            f"every step (donation silently not applied).",
        ))
    if donors:
        out.append(AuditFinding(
            "GA-DONATION", p.name,
            f"{donors} donated leaves lowered as unmatched "
            f"jax.buffer_donor (no output shares their shape/dtype): "
            f"the donation is declared but can never be applied.",
        ))
    return out


def check_donation_compiled(p: Program, mem) -> list[AuditFinding]:
    if p.donated_leaves <= 0 or mem is None:
        return []
    alias = int(getattr(mem, "alias_size_in_bytes", 0))
    if alias <= 0:
        return [AuditFinding(
            "GA-DONATION", p.name,
            f"compiled executable reports alias_size_in_bytes={alias} "
            f"for a program with {p.donated_leaves} donated leaves — "
            f"XLA dropped the aliasing after optimization.",
        )]
    return []


def check_f64(p: Program) -> list[AuditFinding]:
    if _has_f64(p.text):
        line = next((ln.strip() for ln in p.text.splitlines()
                     if _has_f64(ln)), "")
        return [AuditFinding(
            "GA-F64", p.name,
            f"f64 value in the lowered module (dtype policy is "
            f"f32/bf16): e.g. {line[:100]!r}",
        )]
    return []


def check_hostcalls(p: Program) -> list[AuditFinding]:
    out = []
    callbacks = 0
    for target in _custom_calls(p.text):
        if _is_callback(target):
            callbacks += 1
        elif target not in _ALLOWED_CUSTOM_CALLS:
            out.append(AuditFinding(
                "GA-HOSTCALL", p.name,
                f"custom_call @{target} is neither XLA partitioning "
                f"plumbing ({sorted(_ALLOWED_CUSTOM_CALLS)}) nor the "
                f"sanctioned callback — unknown host-call surface.",
            ))
    if callbacks != p.callbacks:
        expect = (f"exactly {p.callbacks} (the observe/stream tap)"
                  if p.callbacks else "none")
        out.append(AuditFinding(
            "GA-HOSTCALL", p.name,
            f"{callbacks} callback custom-call(s) in the module, "
            f"expected {expect}: the telemetry tap is the ONE audited "
            f"host callback, present only in the telemetry=step "
            f"program.",
        ))
    return out


# ---- cross-program identity ------------------------------------------


def _normalize(text: str) -> str:
    # callback backend_configs embed process-local pointers; strip them
    # so fingerprints are stable within a run
    return _BACKEND_CONFIG_RE.sub('backend_config = "_"', text)


def fingerprint(text: str) -> str:
    return hashlib.sha256(_normalize(text).encode()).hexdigest()[:16]


def const_fingerprint(text: str) -> str:
    """Fingerprint with every dense<...> literal masked: two programs
    equal under THIS hash but not under ``fingerprint`` differ only in
    burned-in constants — the Python-scalar-leakage shape."""
    return hashlib.sha256(
        _CONST_RE.sub("dense<_>", _normalize(text)).encode()
    ).hexdigest()[:16]


def near_duplicates(named_texts: list[tuple[str, str]]):
    """[(name_a, name_b)] pairs that differ ONLY in constants."""
    by_const: dict[str, list[tuple[str, str]]] = {}
    for name, text in named_texts:
        by_const.setdefault(const_fingerprint(text), []).append(
            (name, fingerprint(text))
        )
    pairs = []
    for group in by_const.values():
        # one representative per DISTINCT exact fingerprint: byte-equal
        # twins are duplicates (check_identity flags those separately),
        # not the constant-only variant this reports
        rep: dict[str, str] = {}
        for name, fp in group:
            rep.setdefault(fp, name)
        if len(rep) > 1:
            names = list(rep.values())
            pairs.append((names[0], names[1]))
    return pairs


def check_identity(programs: list[Program],
                   predict_expected: int) -> list[AuditFinding]:
    out = []
    lowered = [p for p in programs if p.lowered is not None]
    n_predict = sum(1 for p in lowered if p.name.startswith("predict/"))
    if n_predict != predict_expected:
        out.append(AuditFinding(
            "GA-IDENT", "predict/*",
            f"the ladder lowered {n_predict} predict programs, expected "
            f"rungs x forms = {predict_expected}: a rung or staging "
            f"form fell out of (or leaked into) the warm set.",
        ))
    by_fp: dict[str, list[str]] = {}
    for p in lowered:
        by_fp.setdefault(fingerprint(p.text), []).append(p.name)
    for names in by_fp.values():
        if len(names) > 1:
            out.append(AuditFinding(
                "GA-IDENT", names[0],
                f"programs {names} lower to the IDENTICAL module — "
                f"duplicate registry entries or a collapsed ladder rung "
                f"(each warmed program should be distinct work).",
            ))
    for a, b in near_duplicates([(p.name, p.text) for p in lowered]):
        out.append(AuditFinding(
            "GA-IDENT", a,
            f"programs {a!r} and {b!r} differ ONLY in burned-in "
            f"constants: a Python scalar traced as a constant — at "
            f"runtime every new value of it compiles a fresh program "
            f"(the warm-ladder recompile hazard, CHANGES.md PR 3).",
        ))
    return out


# ---- roofline ledger -------------------------------------------------


def _cost_dict(compiled) -> dict:
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def roofline_entry(compiled) -> dict:
    """One ledger row from XLA's own analyses."""
    cost = _cost_dict(compiled)
    flops = float(cost.get("flops", 0.0))
    nbytes = float(cost.get("bytes accessed", 0.0))
    entry = {
        "flops": flops,
        "bytes": nbytes,
        "intensity_flops_per_byte": round(flops / nbytes, 4) if nbytes
        else 0.0,
        "bytes_per_flop": round(nbytes / flops, 6) if flops else 0.0,
    }
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - backend-optional surface
        mem = None
    if mem is not None:
        entry.update(
            peak_temp_bytes=int(getattr(mem, "temp_size_in_bytes", 0)),
            arg_bytes=int(getattr(mem, "argument_size_in_bytes", 0)),
            out_bytes=int(getattr(mem, "output_size_in_bytes", 0)),
            alias_bytes=int(getattr(mem, "alias_size_in_bytes", 0)),
        )
    return entry


# GA-ROOFLINE slack over the analytic model: cost analysis counts the
# custom-call surface plus glue ops (index prep, the stats reduction's
# scalar outputs), and padding rounds block shapes up — 2x headroom
# stays far below the ~M-fold blowup a rematerialized [N, M, *]
# intermediate causes (M = 8-12), so the check cannot false-positive on
# glue yet cannot miss the failure mode it exists for.
_ROOFLINE_SLACK = 2.0


def check_roofline_budget(p: Program, entry: dict) -> list[AuditFinding]:
    if p.byte_budget <= 0:
        return []
    measured = float(entry.get("bytes", 0.0))
    if measured <= 0:
        # a missing/zero cost-analysis byte count would make this check
        # VACUOUSLY green — the one failure mode a guard must not have.
        # Report it so a backend that stops exposing 'bytes accessed'
        # re-arms the budget instead of silently disarming it.
        return [AuditFinding(
            "GA-ROOFLINE", p.name,
            f"cost analysis reported {measured} accessed bytes for a "
            f"byte-budgeted program — the budget cannot be checked on "
            f"this backend/jax; the roofline gate would be vacuous, "
            f"which is itself a finding (fix the measurement or drop "
            f"the budget explicitly).",
        )]
    if measured > p.byte_budget * _ROOFLINE_SLACK:
        return [AuditFinding(
            "GA-ROOFLINE", p.name,
            f"cost-analysis bytes {measured:.3e} exceed the analytic "
            f"one-round-trip model ({p.byte_budget:.3e} x "
            f"{_ROOFLINE_SLACK} slack) — an [N, M, *] intermediate is "
            f"round-tripping HBM again (the staging cost the fused "
            f"conv exists to remove; ops/pallas_cgconv.py).",
        )]
    return []


# GA-SHARD slack over the analytic per-device model: the budget already
# over-counts (it charges the FULL state incl. optimizer leaves XLA
# drops from a forward program), and a replicated batch lands N x the
# batch term above it (N >= 2) — 1.5x headroom cannot false-positive on
# layout padding yet cannot miss the replication it exists to catch.
_SHARD_SLACK = 1.5


def check_shard_budget(p: Program, mem) -> list[AuditFinding]:
    if p.arg_byte_budget <= 0:
        return []
    if mem is None:
        # memory analysis unavailable on this backend/jax: the gate
        # would be VACUOUSLY green — report it instead of passing (same
        # posture as GA-ROOFLINE's zero-bytes branch)
        return [AuditFinding(
            "GA-SHARD", p.name,
            "memory_analysis() unavailable for a shard-budgeted "
            "program — the replication gate cannot be checked on this "
            "backend/jax; fix the measurement or drop the budget "
            "explicitly.",
        )]
    args = int(getattr(mem, "argument_size_in_bytes", 0))
    if args <= 0:
        # a missing per-device argument size would make this gate
        # vacuously green — the one failure mode a guard must not have
        return [AuditFinding(
            "GA-SHARD", p.name,
            f"memory analysis reported {args} per-device argument "
            f"bytes for a shard-budgeted program — the sharding gate "
            f"cannot be checked on this backend/jax; fix the "
            f"measurement or drop the budget explicitly.",
        )]
    if args > p.arg_byte_budget * _SHARD_SLACK:
        return [AuditFinding(
            "GA-SHARD", p.name,
            f"per-device argument bytes {args:.3e} exceed the "
            f"replicated-params + batch/N model "
            f"({p.arg_byte_budget:.3e} x {_SHARD_SLACK} slack) — the "
            f"batch is being REPLICATED to every device instead of "
            f"batch-axis sharded (the NamedSharding mistake the mesh "
            f"engine exists to avoid; parallel/executor.py).",
        )]
    return []


def run_audit(config: AuditConfig | None = None, *, compile: bool = True,
              programs: list[Program] | None = None, meta: dict | None = None):
    """Lower + audit the entry-program registry.

    -> (findings, ledger, programs). ``compile=False`` runs the
    StableHLO-level checks only (fast: no XLA compile) — the live-repo
    test pin; ``compile=True`` additionally verifies donation survived
    compilation and fills the roofline ledger."""
    import jax

    if programs is None:
        programs, meta = build_entry_programs(config)
    findings = lower_programs(programs)
    predict_expected = (meta or {}).get("predict_programs_expected", 0)
    for p in programs:
        if p.lowered is None:
            continue
        findings += check_donation(p)
        findings += check_f64(p)
        findings += check_hostcalls(p)
    findings += check_identity(programs, predict_expected)

    ledger = {
        "meta": {
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "devices": len(jax.devices()),
            **(meta or {}),
            "skipped": {p.name: p.skip for p in programs
                        if p.skip is not None},
            "gate_keys": list(LEDGER_GATE_KEYS),
        },
        "programs": {},
    }
    if compile:
        for p in programs:
            if p.lowered is None:
                continue
            compiled = p.lowered.compile()
            try:
                mem = compiled.memory_analysis()
            except Exception:  # noqa: BLE001
                mem = None
            findings += check_donation_compiled(p, mem)
            findings += check_shard_budget(p, mem)
            entry = roofline_entry(compiled)
            if p.byte_budget > 0:
                entry["byte_budget"] = p.byte_budget
            if p.arg_byte_budget > 0:
                entry["arg_byte_budget"] = p.arg_byte_budget
            findings += check_roofline_budget(p, entry)
            ledger["programs"][p.name] = entry
    findings.sort(key=lambda f: (f.program, f.check))
    return findings, ledger, programs


# ---- ledger budgets (stdlib-only; bench_regress.py reuses this) ------


def diff_ledgers(old: dict, new: dict, threshold: float = 0.2) -> dict:
    """Budget diff of two AUDIT_LEDGER payloads, mirroring
    bench_regress semantics with the sign flipped: gate keys are
    LOWER-is-better, a program or key missing from the NEW ledger is a
    regression (a budget that stopped being measured is how a
    regression hides).

    Numeric drifts are downgraded to warnings when the two ledgers
    were generated by different jax versions (``version_skew``) — XLA's
    cost model moves between releases; structural drops stay hard
    regressions regardless."""
    rows, regressions, warnings = [], [], []
    old_meta = old.get("meta", {})
    skew = old_meta.get("jax") != new.get("meta", {}).get("jax")
    new_programs = new.get("programs", {})
    for pname, oentry in sorted(old.get("programs", {}).items()):
        nentry = new_programs.get(pname)
        if nentry is None:
            row = {"key": pname, "old": "present", "new": None,
                   "note": "program DROPPED from the new ledger"}
            rows.append(row)
            regressions.append(row)
            continue
        for key in LEDGER_GATE_KEYS:
            o, n = oentry.get(key), nentry.get(key)
            if o is None and n is None:
                continue
            row = {"key": f"{pname}.{key}", "old": o, "new": n}
            if n is None:
                row["note"] = "key DROPPED from the new ledger"
                regressions.append(row)
            elif o and o > 0:
                ratio = n / o
                row["ratio"] = round(ratio, 4)
                if ratio > 1.0 + threshold:
                    row["note"] = (f"REGRESSION: {100 * (ratio - 1):.1f}% "
                                   f"above budget")
                    (warnings if skew else regressions).append(row)
            elif o == 0 and n > 0:
                # a zero budget has no ratio; any nonzero value of a
                # lower-is-better key is how e.g. the expander starts
                # materializing temps without anyone noticing
                row["note"] = f"REGRESSION: budget was 0, now {n}"
                (warnings if skew else regressions).append(row)
            rows.append(row)
    return {"rows": rows, "regressions": regressions,
            "warnings": warnings, "version_skew": skew}


def write_ledger(ledger: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(ledger, f, indent=1, sort_keys=True, allow_nan=False)
        f.write("\n")


def load_ledger(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
