"""Static analysis + runtime race detection for the repo's invariants.

Six PRs of hard-won correctness rules — donation/aliasing safety, the
stop-event thread-shutdown contract, counts-under-lock scrapes, the
zero-post-warmup-recompile discipline — lived only in CHANGES.md prose
and scattered tests. This package makes them *mechanical*:

- ``engine`` + ``rules``: the AST linter behind ``graftcheck.py`` —
  repo-specific rules, each carrying the CHANGES.md incident that
  motivated it, with ``# graftcheck: disable=RULE -- why`` escape
  hatches that REQUIRE a justification string (see INVARIANTS.md);
- ``racecheck``: the opt-in (``CGNN_TPU_RACECHECK=1``) runtime
  companion — instrumented locks that record acquisition order per
  thread and flag lock-order inversions, cross-thread unprotected
  access to registered shared fields, and a deadlock watchdog that
  dumps every thread's stack (with names) when a serving thread goes
  silent past a bound. Zero overhead when the env gate is off.

Everything in ``engine``/``rules`` is stdlib-only (ast + tokenize): the
CI ``static-analysis`` job runs without jax installed.
"""

from cgnn_tpu.analysis.engine import (
    Finding,
    check_file,
    check_paths,
    default_targets,
)
from cgnn_tpu.analysis.rules import RULES

__all__ = [
    "Finding",
    "RULES",
    "check_file",
    "check_paths",
    "default_targets",
]
