"""Runtime race / lock-discipline detector (the graftcheck companion).

Static rules (rules.py) catch what an AST can see; this module catches
what only execution shows: the ORDER locks are really taken in, fields
really shared across threads, and threads that really wedge. Opt-in via
``CGNN_TPU_RACECHECK=1`` — the serve-smoke CI leg runs the full
64-client load under it and asserts zero inversions/violations — and
ZERO overhead when off: ``make_lock`` returns a plain
``threading.Lock`` and every hook is a no-op (PERF.md §14).

Three detectors:

- **Lock-order inversions** (:func:`make_lock` / :func:`make_condition`):
  every successful acquisition records held-lock -> new-lock edges per
  thread; a pair of locks observed in BOTH orders is a deadlock waiting
  for the right interleaving — flagged immediately, with the thread
  names that produced each direction.
- **Unprotected shared-field access** (:func:`watch_fields`): registered
  fields of an object (the server's counts/latency buffers) are checked
  on every get/set — a touch from a thread other than the registering
  one without the guarding lock held is a violation. This is the PR-6
  scrape bug as a runtime tripwire.
- **Deadlock watchdog** (:func:`start_watchdog` + :func:`heartbeat`):
  loops that matter (serve dispatch, pack workers, the reload watcher)
  call ``heartbeat()`` each iteration; a registered thread silent past
  the bound triggers a faulthandler dump of EVERY thread's stack,
  prefixed with an ident -> thread-name map so the dump is attributable
  (thread names are a graftcheck rule for exactly this reason).

``report()`` aggregates all three; the loadgen folds it into the SLO
report and fails the run on any nonzero count.
"""

from __future__ import annotations

import faulthandler
import io
import os
import sys
import threading
import time

ENV_VAR = "CGNN_TPU_RACECHECK"

_enabled = os.environ.get(ENV_VAR, "") not in ("", "0", "false", "no")

_state_lock = threading.Lock()  # guards the registries below
_held = threading.local()       # per-thread list of held _LockInfo
_edges: dict = {}               # (id_a, id_b) -> (name_a, name_b, thread)
_inversions: list = []
_inversion_keys: set = set()
_violations: list = []
_beats: dict = {}               # thread name -> (last beat, ident)
_beats_seen: set = set()        # every name that EVER heartbeated (never
                                # pruned: the "watchdog watched something"
                                # assertion must survive clean exits)
_watchdog = None


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    """Flip the gate programmatically (tests; production uses the env
    var at import). Locks made while off stay plain — only NEW locks
    are instrumented."""
    global _enabled
    _enabled = bool(on)


def reset() -> None:
    """Drop all recorded state (test isolation)."""
    global _watchdog
    with _state_lock:
        _edges.clear()
        _inversions.clear()
        _inversion_keys.clear()
        _violations.clear()
        _beats.clear()
        _beats_seen.clear()
    if _watchdog is not None:
        _watchdog.stop()
        _watchdog = None


class _LockInfo:
    __slots__ = ("name", "lock_id")

    def __init__(self, name: str, lock_id: int):
        self.name = name
        self.lock_id = lock_id


def _held_list() -> list:
    lst = getattr(_held, "list", None)
    if lst is None:
        lst = _held.list = []
    return lst


def _note_acquired(info: _LockInfo) -> None:
    held = _held_list()
    tname = threading.current_thread().name
    if held:
        with _state_lock:
            for h in held:
                if h.lock_id == info.lock_id:
                    continue  # re-entrant acquire of the same lock
                edge = (h.lock_id, info.lock_id)
                back = (info.lock_id, h.lock_id)
                _edges.setdefault(edge, (h.name, info.name, tname))
                if back in _edges:
                    key = tuple(sorted(edge))
                    if key not in _inversion_keys:
                        _inversion_keys.add(key)
                        a_name, b_name, other = _edges[back]
                        _inversions.append({
                            "locks": sorted((h.name, info.name)),
                            "order_a": f"{h.name} -> {info.name} "
                                       f"in {tname}",
                            "order_b": f"{a_name} -> {b_name} "
                                       f"in {other}",
                        })
    held.append(info)


def _note_released(info: _LockInfo) -> None:
    held = _held_list()
    for i in range(len(held) - 1, -1, -1):
        if held[i] is info or held[i].lock_id == info.lock_id:
            del held[i]
            break


class InstrumentedLock:
    """A Lock/RLock wrapper recording acquisition order per thread.

    Duck-compatible with ``threading.Lock`` (acquire/release/context
    manager) and with ``threading.Condition``'s lock protocol
    (``_is_owned`` is provided so Condition never runs its acquire(0)
    probe, which would record phantom acquisitions).
    """

    def __init__(self, name: str, reentrant: bool = False):
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self._info = _LockInfo(name, id(self))
        self._owner: int | None = None
        self._depth = 0
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            ident = threading.get_ident()
            if self._owner == ident:
                self._depth += 1
            else:
                self._owner = ident
                self._depth = 1
                _note_acquired(self._info)
        return ok

    def release(self) -> None:
        ident = threading.get_ident()
        if self._owner == ident:
            self._depth -= 1
            if self._depth == 0:
                self._owner = None
                _note_released(self._info)
        self._lock.release()

    def held_by_current(self) -> bool:
        return self._owner == threading.get_ident()

    # Condition's lock protocol: _is_owned avoids the acquire(0) probe
    # (which would record phantom acquisitions); _release_save /
    # _acquire_restore make recursive holds survive Condition.wait()
    def _is_owned(self) -> bool:
        return self.held_by_current()

    def _release_save(self):
        depth = self._depth
        self._depth = 0
        self._owner = None
        _note_released(self._info)
        for _ in range(depth):
            self._lock.release()
        return depth

    def _acquire_restore(self, depth) -> None:
        for _ in range(depth):
            self._lock.acquire()
        self._owner = threading.get_ident()
        self._depth = depth
        _note_acquired(self._info)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._owner is not None


def make_lock(name: str):
    """A named, instrumented lock when racecheck is on; a plain
    ``threading.Lock`` (zero overhead) when off."""
    if not _enabled:
        return threading.Lock()
    return InstrumentedLock(name)


def make_condition(name: str):
    """A Condition over an instrumented (reentrant) lock when on."""
    if not _enabled:
        return threading.Condition()
    return threading.Condition(InstrumentedLock(name, reentrant=True))


# ---- shared-field watching ------------------------------------------

_WATCH_ATTR = "__racecheck_watch__"


def watch_fields(obj, lock, fields) -> None:
    """Register ``fields`` of ``obj`` as guarded by ``lock``: any
    get/set from a thread other than the registering one without the
    lock held records a violation. No-op unless racecheck is on AND
    ``lock`` is an :class:`InstrumentedLock` (the plain-lock fallback
    cannot answer 'held by current thread?').

    Implementation: the instance's class is swapped for a one-off
    subclass overriding ``__getattribute__``/``__setattr__`` — the
    overhead lands only on watched instances, only when enabled.
    """
    if not _enabled or not isinstance(lock, InstrumentedLock):
        return
    fields = frozenset(fields)
    owner_thread = threading.current_thread().name
    cls = type(obj)

    def _check(name: str, mode: str) -> None:
        t = threading.current_thread().name
        if t == owner_thread or lock.held_by_current():
            return
        with _state_lock:
            if len(_violations) < 1000:
                _violations.append({
                    "class": cls.__name__,
                    "field": name,
                    "mode": mode,
                    "thread": t,
                    "lock": lock.name,
                })

    class _Watched(cls):  # type: ignore[misc, valid-type]
        def __getattribute__(self, name):
            if name in fields:
                _check(name, "read")
            return super().__getattribute__(name)

        def __setattr__(self, name, value):
            if name in fields:
                _check(name, "write")
            super().__setattr__(name, value)

    _Watched.__name__ = cls.__name__
    _Watched.__qualname__ = cls.__qualname__
    setattr(_Watched, _WATCH_ATTR, True)
    obj.__class__ = _Watched


# ---- heartbeats + deadlock watchdog ---------------------------------


def heartbeat() -> None:
    """Record 'this thread is alive and looping'. First beat registers
    the thread with the watchdog (by NAME — graftcheck's GC-THREADNAME
    rule exists so this registry is readable). No-op when off."""
    if not _enabled:
        return
    t = threading.current_thread()
    with _state_lock:
        _beats[t.name] = (time.monotonic(), t.ident)
        _beats_seen.add(t.name)


class Watchdog:
    """Dump every thread's stack when a heartbeating thread goes silent.

    ``bound_s`` is the silence tolerance; a thread that exited cleanly
    (no live thread with its ident) is unregistered, not reported. The
    dump goes to ``sink`` (default stderr) prefixed with an
    ident -> name map so faulthandler's nameless stacks are
    attributable.
    """

    def __init__(self, bound_s: float = 30.0, interval_s: float | None = None,
                 sink=None, log_fn=None):
        self.bound_s = float(bound_s)
        self.interval_s = (interval_s if interval_s is not None
                           else max(0.2, self.bound_s / 4))
        self.sink = sink
        self._log = log_fn or (lambda m: print(m, file=sys.stderr))
        self._stop = threading.Event()
        self.dumps = 0
        self.stalled: list = []
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="racecheck-watchdog"
        )

    def start(self) -> "Watchdog":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def check_once(self, now: float | None = None) -> list:
        """The synchronous unit: names silent past the bound right now
        (dead threads pruned, not reported)."""
        now = time.monotonic() if now is None else now
        # ident -> name, not a bare ident set: CPython reuses thread
        # idents, so "ident still alive" alone would pin a cleanly
        # exited thread's stale beat to an unrelated newcomer and dump
        # a spurious deadlock 30 s later
        alive = {t.ident: t.name for t in threading.enumerate()}
        stalled = []
        with _state_lock:
            for name in list(_beats):
                last, ident = _beats[name]
                if alive.get(ident) != name:
                    del _beats[name]  # clean exit, not a deadlock
                    continue
                if now - last > self.bound_s:
                    stalled.append(name)
        return stalled

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            stalled = self.check_once()
            if stalled:
                self.dumps += 1
                self.stalled.extend(n for n in stalled
                                    if n not in self.stalled)
                self.dump(stalled)
                # one dump per stall: re-arm the beats so a recovered
                # thread isn't re-reported every tick
                now = time.monotonic()
                with _state_lock:
                    for name in stalled:
                        if name in _beats:
                            _beats[name] = (now, _beats[name][1])

    def dump(self, stalled) -> None:
        sink = self.sink or sys.stderr
        names = {t.ident: t.name for t in threading.enumerate()}
        sink.write(
            f"\n=== racecheck deadlock watchdog: thread(s) {stalled} "
            f"silent > {self.bound_s:.1f}s ===\n"
        )
        for ident, name in sorted(names.items(), key=lambda kv: kv[1]):
            sink.write(f"  thread 0x{ident:x} = {name}\n")
        sink.flush()
        try:
            faulthandler.dump_traceback(file=sink, all_threads=True)
        except (ValueError, io.UnsupportedOperation):
            # sink without a real fd (StringIO in tests): names + the
            # stall report above are still the attributable part
            pass
        sink.flush()
        self._log(
            f"racecheck: WATCHDOG dump #{self.dumps + 0} — {stalled} "
            f"silent past {self.bound_s:.1f}s (see stderr for stacks)"
        )


def start_watchdog(bound_s: float = 30.0, **kw):
    """Start the singleton watchdog (None when racecheck is off).

    A later call re-arms the existing singleton with the new bound and
    log/sink targets rather than silently ignoring them: a second
    server started in the same process must not leave stall logs wired
    to (and the closure pinning) a drained predecessor.
    """
    global _watchdog
    if not _enabled:
        return None
    if _watchdog is None:
        _watchdog = Watchdog(bound_s=bound_s, **kw).start()
    else:
        _watchdog.bound_s = float(bound_s)
        _watchdog.interval_s = (kw.get("interval_s")
                                or max(0.2, _watchdog.bound_s / 4))
        if kw.get("log_fn") is not None:
            _watchdog._log = kw["log_fn"]
        if kw.get("sink") is not None:
            _watchdog.sink = kw["sink"]
    return _watchdog


# ---- reporting -------------------------------------------------------


def report() -> dict:
    """The aggregate the loadgen folds into its SLO report."""
    with _state_lock:
        inversions = list(_inversions)
        violations = list(_violations)
        beats = sorted(_beats)
        seen = sorted(_beats_seen)
    dumps = 0 if _watchdog is None else _watchdog.dumps
    stalled = [] if _watchdog is None else list(_watchdog.stalled)
    return {
        "enabled": _enabled,
        "inversions": inversions,
        "violations": violations,
        "deadlock_dumps": dumps,
        "stalled_threads": stalled,
        # live beats only (cleanly exited threads pruned) vs every name
        # that ever registered — asserts about "the watchdog watched
        # SOMETHING" must use the latter or they race thread shutdown
        "heartbeating_threads": beats,
        "heartbeats_seen": seen,
        "clean": not (inversions or violations or dumps),
    }
