"""The graftcheck rule catalog: repo-specific AST checks.

Each rule encodes an invariant this repo already paid for once; the
message cites the CHANGES.md incident so a finding explains *why* it is
a bug here, not just what pattern matched. INVARIANTS.md is the prose
catalog. Rules are deliberately narrow — a linter the tree cannot run
clean against gets disabled, not obeyed — and every rule has a
``# graftcheck: disable=RULE -- justification`` escape hatch (engine.py)
for the audited exceptions.

Stdlib-only (ast): no jax import, so the CI job runs anywhere.
"""

from __future__ import annotations

import ast
import dataclasses
import re

# rule id -> one-line description (the --list-rules output; INVARIANTS.md
# carries the full incident write-ups)
RULES = {
    "GC-ALIAS": (
        "device_get/device_put aliasing: on CPU jax.device_get returns "
        "views ALIASING device buffers (the PR-2 checkpoint-corruption "
        "incident: donated train steps mutated checkpoint bytes "
        "mid-write) and device_put(x, x.sharding) aliases instead of "
        "copying (the PR-1 warm() donation trap). Fetches must copy "
        "(np.array / tree_map(np.array, ...)), be a bare fence "
        "statement, or carry an audited disable."
    ),
    "GC-HOSTCALL": (
        "host callback / Python side effect staged inside a jitted body "
        "outside the sanctioned telemetry tap (observe/stream.py): "
        "host calls in traced code either burn a trace-time constant or "
        "stage unordered side effects the PR-1 stream was built to "
        "contain."
    ),
    "GC-RECOMPILE": (
        "recompile hazard: data-dependent-shape ops inside a jitted "
        "body, or a jit-callable call site passing Python scalars / "
        "shape expressions as traced args — both defeat the warm shape "
        "ladder's zero-post-warmup-recompile pin (PR 3)."
    ),
    "GC-THREAD": (
        "thread target loops forever with no stop-event/sentinel exit "
        "path: the loader/pipeline shutdown contract (PR 2/PR 4) — a "
        "consumer that abandons the stream must release every helper "
        "thread within one timeout tick."
    ),
    "GC-THREADNAME": (
        "threading.Thread created without a stable name=: racecheck "
        "reports and faulthandler deadlock dumps are unattributable "
        "without one (PR 7)."
    ),
    "GC-LOCKSHARE": (
        "a field mutated under the class lock is read/written from a "
        "method that never acquires it — the PR-6 scrape bug (counts "
        "dict resized mid-iteration under a concurrent _count), found "
        "mechanically this time. Also flags read-modify-write (+=) on "
        "shared fields outside any lock in a lock-bearing class."
    ),
    "GC-BLOCKING": (
        "blocking call (block_until_ready, device_get, zero-arg "
        "queue.get, join/wait without timeout, sleep) inside a held-lock "
        "region: every other thread touching that lock stalls behind "
        "device/IO latency — the serving-fleet deadlock shape."
    ),
    "GC-JSONFINITE": (
        "float telemetry serialized without the non-finite->null guard: "
        "bare NaN/Infinity tokens are invalid strict JSON (the PR-6 "
        "metrics_live.jsonl fix) — route payloads through jsonfinite() "
        "or pass allow_nan=False to fail loudly."
    ),
    "GC-DTYPE": (
        "float64 creep into jitted code: np.float64 / 'float64' dtype "
        "literals, or dtype-less np.array/np.zeros/np.ones/np.empty/"
        "np.full/np.arange (numpy defaults to float64) inside a jitted "
        "body — under x64 these double the HBM bytes of the exact "
        "memory-bound paths the roofline ledger budgets; the graftaudit "
        "GA-F64 gate proves compiled programs stay f64-free "
        "(CHANGES.md PR 8)."
    ),
    "GC-DISABLE": (
        "a graftcheck disable comment without a justification string "
        "(or naming an unknown rule): escape hatches must say WHY "
        "(INVARIANTS.md policy)."
    ),
    "GC-PARSE": (
        "file does not parse: graftcheck cannot vouch for invariants "
        "in code the AST cannot see — an unparseable file is a finding "
        "in its own right, never a silent skip."
    ),
}

# the one module allowed to stage host callbacks into jitted code: the
# PR-1 telemetry tap (unordered jax.debug.callback, bit-identical
# on/off, pinned by test)
_SANCTIONED_CALLBACK_SUFFIX = "observe/stream.py"

_CALLBACK_NAMES = ("debug.print", "debug.callback", "io_callback",
                   "pure_callback")
_HOSTCALLS_IN_JIT = ("print", "open", "input")
_HOSTCALL_DOTTED = ("time.time", "time.perf_counter", "time.monotonic")
_DATA_DEP_SHAPE = ("nonzero", "unique", "argwhere", "flatnonzero")
# numpy constructors that default to float64 when dtype is omitted
_NP_F64_DEFAULT = ("array", "zeros", "ones", "empty", "full", "arange",
                   "linspace", "eye")
# a dtype passed positionally (np.zeros(4, np.float32)) still counts as
# supplied — match expressions that read as dtype names
_DTYPE_NAME_RE = re.compile(
    r"^(float|int|uint|complex)\d+$|^(bfloat16|bool_|float_|int_)$")
_LOCK_FACTORIES = ("Lock", "RLock", "Condition", "make_lock",
                   "make_condition")
_COPY_WRAPPERS = ("array", "float", "int", "bool", "copy", "deepcopy")
_FINITE_GUARDS = ("finite", "jsonsafe", "sanitiz")


@dataclasses.dataclass
class RawFinding:
    rule: str
    line: int
    end_line: int
    message: str


def _dotted(node: ast.AST) -> str:
    """'jax.debug.callback' for nested Attribute/Name chains ('' when the
    expression is not a plain dotted name)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _tail(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def _same_expr(a: ast.AST, b: ast.AST) -> bool:
    return ast.dump(a) == ast.dump(b)


def _raw(rule: str, node: ast.AST, message: str) -> RawFinding:
    return RawFinding(rule, node.lineno,
                      getattr(node, "end_lineno", node.lineno), message)


# ---- shared module inventory ----------------------------------------


def _jitted_functions(tree: ast.Module):
    """(jitted function defs, jitted callable names, names jitted WITH
    static args) resolvable inside this module.

    Covers ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorators,
    ``x = jax.jit(f)`` bindings, bare ``jax.jit(f)`` calls on local
    defs, and ``lax.scan(body, ...)`` bodies (scanned code is traced
    code). Cross-module jitting (a make_* factory jitted by its caller)
    is invisible to a single-file pass — accepted coverage gap.
    """
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    jitted: dict[str, ast.AST] = {}
    jitted_names: set[str] = set()
    static_names: set[str] = set()

    def is_jit(call: ast.Call) -> bool:
        d = _dotted(call.func)
        if d == "jax.jit":
            return True
        # partial(jax.jit, ...) used as a decorator factory
        if _tail(d) == "partial" and call.args:
            return _dotted(call.args[0]) == "jax.jit"
        return False

    def has_static(call: ast.Call) -> bool:
        return any(kw.arg in ("static_argnums", "static_argnames")
                   for kw in call.keywords)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _dotted(dec) == "jax.jit":
                    jitted[node.name] = node
                    jitted_names.add(node.name)
                elif isinstance(dec, ast.Call) and is_jit(dec):
                    jitted[node.name] = node
                    jitted_names.add(node.name)
                    if has_static(dec):
                        static_names.add(node.name)
        if isinstance(node, ast.Call):
            target = None
            if is_jit(node) and node.args:
                arg0 = node.args[0]
                # partial(jax.jit, f)? jax.jit(f) is the common shape
                if _dotted(node.func) == "jax.jit":
                    target = arg0
                elif len(node.args) > 1:
                    target = node.args[1]
            elif _tail(_dotted(node.func)) == "scan" and node.args:
                target = node.args[0]
            if isinstance(target, ast.Name):
                jitted_names.add(target.id)
                if target.id in defs:
                    jitted[target.id] = defs[target.id]
                if is_jit(node) and has_static(node):
                    static_names.add(target.id)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if is_jit(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jitted_names.add(t.id)
                        if has_static(node.value):
                            static_names.add(t.id)
    return jitted, jitted_names, static_names


# ---- per-rule checks -------------------------------------------------


def _check_alias(tree: ast.Module) -> list[RawFinding]:
    out = []
    # statement-only device_get calls are fences (train/loop.py's window
    # fence); their result never escapes, so aliasing cannot bite
    fence_calls = {
        id(stmt.value)
        for stmt in ast.walk(tree)
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)
    }
    copied_calls = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        tail = _tail(_dotted(node.func))
        if tail in _COPY_WRAPPERS:
            for a in node.args:
                copied_calls.add(id(a))
        if tail == "tree_map" and node.args:
            # jax.tree_util.tree_map(np.array, device_get(...)) is the
            # PR-2 checkpoint fix shape: a per-leaf copy barrier
            if _tail(_dotted(node.args[0])) in ("array", "copy"):
                for a in node.args[1:]:
                    copied_calls.add(id(a))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        tail = _tail(d)
        if tail == "device_put" and len(node.args) >= 2:
            dst = node.args[1]
            if (isinstance(dst, ast.Attribute) and dst.attr == "sharding"
                    and _same_expr(dst.value, node.args[0])):
                out.append(_raw(
                    "GC-ALIAS", node,
                    "device_put(x, x.sharding) returns an ALIAS of x, not "
                    "a copy — donating the result donates x too (the PR-1 "
                    "warm() trap; CHANGES.md PR 1). Copy-then-place: "
                    "device_put(jnp.array(x), x.sharding).",
                ))
        if tail == "device_get":
            if id(node) in fence_calls or id(node) in copied_calls:
                continue
            out.append(_raw(
                "GC-ALIAS", node,
                "unaudited jax.device_get: on CPU backends the result "
                "ALIASES device buffers, and a donated step mutates them "
                "under you (the PR-2 checkpoint-corruption incident; "
                "CHANGES.md PR 2). Wrap in np.array(...) / "
                "tree_map(np.array, ...) (np.asarray does NOT copy), "
                "use it as a bare fence statement, or add a disable "
                "with the audit justification.",
            ))
    return out


def _check_hostcall(tree: ast.Module, path: str) -> list[RawFinding]:
    out = []
    sanctioned = path.replace("\\", "/").endswith(
        _SANCTIONED_CALLBACK_SUFFIX)
    jitted, _, _ = _jitted_functions(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if any(d.endswith(cb) for cb in _CALLBACK_NAMES):
            if not sanctioned:
                out.append(_raw(
                    "GC-HOSTCALL", node,
                    f"host callback {d or 'callback'}(...) outside the "
                    "sanctioned telemetry tap (observe/stream.py): the "
                    "PR-1 stream is the ONE audited place side effects "
                    "are staged into jitted code (unordered, muted at "
                    "warmup, bit-identical on/off).",
                ))
    for fn in jitted.values():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d in _HOSTCALLS_IN_JIT or d in _HOSTCALL_DOTTED:
                out.append(_raw(
                    "GC-HOSTCALL", node,
                    f"{d}(...) inside the jitted body {fn.name!r}: host "
                    "calls in traced code run at TRACE time (a burned-in "
                    "constant or a once-per-compile side effect), not "
                    "per step — route telemetry through the "
                    "observe/stream.py tap (CHANGES.md PR 1).",
                ))
    return out


def _check_recompile(tree: ast.Module) -> list[RawFinding]:
    out = []
    jitted, jitted_names, static_names = _jitted_functions(tree)
    for fn in jitted.values():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            tail = _tail(d)
            if tail in _DATA_DEP_SHAPE and d.split(".")[0] in (
                    "jnp", "jax", "np", "numpy"):
                out.append(_raw(
                    "GC-RECOMPILE", node,
                    f"{d}(...) inside the jitted body {fn.name!r} has a "
                    "data-dependent output shape: it cannot stage into "
                    "one fixed program, so every batch re-traces — the "
                    "warm shape ladder's zero-post-warmup-recompile pin "
                    "(CHANGES.md PR 3) is built on fixed shapes.",
                ))
            if (tail == "where" and d.split(".")[0] in ("jnp", "jax")
                    and len(node.args) == 1):
                out.append(_raw(
                    "GC-RECOMPILE", node,
                    f"single-arg {d}(cond) inside the jitted body "
                    f"{fn.name!r} returns data-dependent-shape indices; "
                    "use the three-arg select form.",
                ))
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jitted_names
                and node.func.id not in static_names):
            continue
        for arg in node.args:
            hazard = None
            if (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, (int, float))
                    and not isinstance(arg.value, bool)):
                hazard = f"Python scalar {arg.value!r}"
            elif (isinstance(arg, ast.Call)
                    and _dotted(arg.func) == "len"):
                hazard = "len(...)"
            elif (isinstance(arg, ast.Subscript)
                    and isinstance(arg.value, ast.Attribute)
                    and arg.value.attr == "shape"):
                hazard = "a .shape[...] expression"
            if hazard:
                out.append(_raw(
                    "GC-RECOMPILE", node,
                    f"jitted callable {node.func.id!r} called with "
                    f"{hazard} as a traced argument: weak-typed scalars "
                    "and shape-derived values silently re-trace when "
                    "their dtype or value class shifts — pass device "
                    "arrays, or declare it static_argnums at the jit "
                    "site (warm-ladder discipline, CHANGES.md PR 3).",
                ))
    return out


def _loop_has_exit(loop: ast.While) -> bool:
    """A ``while True`` loop passes when it has a stop-event check or a
    sentinel-style conditional exit (the loader/pipeline contract:
    `if item is _STOP: return`, `stop.is_set()`, `stop.wait(t)`)."""
    for node in ast.walk(loop):
        if isinstance(node, (ast.Return, ast.Break)):
            return True
        if isinstance(node, ast.Call):
            tail = _tail(_dotted(node.func))
            if tail in ("is_set", "wait"):
                return True
    return False


def _thread_targets(tree: ast.Module):
    """[(Thread() call node, target fn def or None)] for every
    threading.Thread constructed in this module."""
    defs: dict[str, ast.AST] = {}
    methods: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef):
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods[item.name] = item
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _tail(_dotted(node.func)) == "Thread"):
            continue
        target = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
        fn = None
        if isinstance(target, ast.Name):
            fn = defs.get(target.id)
        elif (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            fn = methods.get(target.attr)
        out.append((node, fn))
    return out


def _check_thread(tree: ast.Module) -> list[RawFinding]:
    out = []
    for call, fn in _thread_targets(tree):
        has_name = any(kw.arg == "name" for kw in call.keywords)
        if not has_name:
            out.append(_raw(
                "GC-THREADNAME", call,
                "threading.Thread without a stable name=: racecheck "
                "reports and the deadlock watchdog's faulthandler dumps "
                "attribute stacks by thread name (CHANGES.md PR 7) — "
                "anonymous Thread-5 is undebuggable at 3am.",
            ))
        if fn is None:
            continue
        for loop in ast.walk(fn):
            if (isinstance(loop, ast.While)
                    and isinstance(loop.test, ast.Constant)
                    and loop.test.value is True
                    and not _loop_has_exit(loop)):
                out.append(_raw(
                    "GC-THREAD", loop,
                    f"thread target {fn.name!r} loops forever with no "
                    "stop-event / sentinel exit path: the loader "
                    "contract (CHANGES.md PR 2/PR 4) — every blocking "
                    "helper loop must be bounded by a stop event or a "
                    "queue sentinel so an abandoning consumer releases "
                    "it within one timeout tick.",
                ))
    return out


# ---- lock discipline -------------------------------------------------


class _LockScan(ast.NodeVisitor):
    """Per-method field accesses, split by under-lock / outside-lock."""

    def __init__(self, lock_attrs: set[str]):
        self.lock_attrs = lock_attrs
        self.depth = 0
        self.writes_locked: set[str] = set()
        self.writes_unlocked: dict[str, ast.AST] = {}
        self.reads_unlocked: dict[str, ast.AST] = {}
        self.aug_unlocked: dict[str, ast.AST] = {}
        self.calls_acquire = False
        self.locked_regions: list = []  # (with node, lock expr)

    def _is_lock_expr(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in self.lock_attrs)

    def visit_With(self, node: ast.With):
        locked = any(self._is_lock_expr(item.context_expr)
                     for item in node.items)
        if locked:
            for item in node.items:
                if self._is_lock_expr(item.context_expr):
                    self.locked_regions.append((node, item.context_expr))
            self.depth += 1
            self.generic_visit(node)
            self.depth -= 1
        else:
            self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in ("acquire", "release")
                and self._is_lock_expr(node.func.value)):
            self.calls_acquire = True
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and node.attr not in self.lock_attrs):
            if isinstance(node.ctx, ast.Store):
                if self.depth:
                    self.writes_locked.add(node.attr)
                else:
                    self.writes_unlocked.setdefault(node.attr, node)
            elif isinstance(node.ctx, ast.Load) and not self.depth:
                self.reads_unlocked.setdefault(node.attr, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        t = node.target
        if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            if self.depth:
                self.writes_locked.add(t.attr)
            else:
                self.aug_unlocked.setdefault(t.attr, node)
                self.writes_unlocked.setdefault(t.attr, t)
        self.generic_visit(node)


def _class_locks(cls: ast.ClassDef) -> set[str]:
    locks = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Call)
                and _tail(_dotted(node.value.func)) in _LOCK_FACTORIES):
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                locks.add(t.attr)
    return locks


def _check_lockshare(tree: ast.Module) -> list[RawFinding]:
    out = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _class_locks(cls)
        if not locks:
            continue
        scans: dict[str, _LockScan] = {}
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            scan = _LockScan(locks)
            for stmt in item.body:
                scan.visit(stmt)
            scans[item.name] = scan
        # guarded = fields MUTATED under the lock anywhere outside
        # __init__ (reads under lock don't make a field shared: plenty
        # of immutable config is read inside critical sections)
        guarded: set[str] = set()
        for name, scan in scans.items():
            if name != "__init__" and not name.endswith("_locked"):
                guarded |= scan.writes_locked
        for name, scan in scans.items():
            if (name == "__init__" or name.endswith("_locked")
                    or scan.calls_acquire):
                # *_locked methods run with the lock held by contract;
                # acquire()-style methods manage the lock imperatively
                # (too coarse to track per-access)
                continue
            hits = {}
            for f, node in scan.reads_unlocked.items():
                if f in guarded:
                    hits[f] = node
            for f, node in scan.writes_unlocked.items():
                if f in guarded:
                    hits[f] = node
            for f, node in sorted(hits.items()):
                out.append(_raw(
                    "GC-LOCKSHARE", node,
                    f"{cls.name}.{f} is mutated under self lock(s) "
                    f"{sorted(locks)} elsewhere but accessed here "
                    f"({name}) without acquiring it — the PR-6 scrape "
                    "bug shape (CHANGES.md PR 6: a concurrent _count "
                    "resized counts mid-iteration and cost the scrape "
                    "the whole provider). Read/write it under the lock, "
                    "or rename the method *_locked if callers hold it.",
                ))
            for f, node in sorted(scan.aug_unlocked.items()):
                if f in hits or f in guarded:
                    continue  # already reported above
                out.append(_raw(
                    "GC-LOCKSHARE", node,
                    f"read-modify-write {cls.name}.{f} += ... outside "
                    "any lock in a lock-bearing class: += is not atomic "
                    "across threads (lost updates under the GIL's "
                    "bytecode boundaries) — move it under "
                    f"{sorted(locks)} or document why only one thread "
                    "ever writes it.",
                ))
    return out


def _check_blocking(tree: ast.Module) -> list[RawFinding]:
    out = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _class_locks(cls)
        if not locks:
            continue
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            scan = _LockScan(locks)
            for stmt in item.body:
                scan.visit(stmt)
            for region, lock_expr in scan.locked_regions:
                for node in ast.walk(region):
                    if not isinstance(node, ast.Call):
                        continue
                    d = _dotted(node.func)
                    tail = _tail(d)
                    blocking = None
                    if tail in ("block_until_ready", "device_get"):
                        blocking = f"{d}(...)"
                    elif tail == "sleep":
                        blocking = f"{d}(...)"
                    elif (tail == "get" and not node.args
                            and not any(kw.arg == "timeout"
                                        for kw in node.keywords)):
                        blocking = "queue .get() with no timeout"
                    elif tail in ("join", "wait"):
                        # cond.wait on the HELD lock releases it (fine);
                        # joining/waiting anything else under a lock
                        # without a timeout blocks every other holder
                        receiver = (node.func.value
                                    if isinstance(node.func, ast.Attribute)
                                    else None)
                        on_this_lock = (receiver is not None
                                        and _same_expr(receiver, lock_expr))
                        has_timeout = (bool(node.args) or any(
                            kw.arg == "timeout" for kw in node.keywords))
                        if not on_this_lock and not has_timeout:
                            blocking = f".{tail}() with no timeout"
                    if blocking:
                        out.append(_raw(
                            "GC-BLOCKING", node,
                            f"{blocking} inside the held-lock region "
                            f"({cls.name}.{item.name}): every thread "
                            "touching that lock stalls behind device/IO "
                            "latency — the PR-6 counts-under-lock rule "
                            "is 'copy under the lock, work outside it' "
                            "(CHANGES.md PR 6).",
                        ))
    return out


def _check_dtype(tree: ast.Module) -> list[RawFinding]:
    """GC-DTYPE: f64 creep inside jitted bodies.

    Two shapes, both scoped to code _jitted_functions can see traced:
    explicit float64 (``np.float64`` / ``jnp.float64`` attributes,
    ``'float64'``/``'f64'`` dtype strings), and dtype-less numpy
    constructors (``np.array``/``zeros``/``ones``/... default to
    float64, silently doubling HBM bytes under x64). jnp constructors
    without dtype are fine — they default to the f32 weak type. The
    graftaudit GA-F64 gate proves the same policy on the COMPILED
    programs; this rule points at the source line that caused it.
    """

    def supplies_dtype(call: ast.Call) -> bool:
        def looks_like_dtype(node: ast.AST) -> bool:
            if isinstance(node, ast.Attribute):
                return bool(_DTYPE_NAME_RE.match(node.attr))
            if isinstance(node, ast.Name):
                return bool(_DTYPE_NAME_RE.match(node.id))
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                return bool(_DTYPE_NAME_RE.match(node.value))
            return False

        return (any(kw.arg == "dtype" for kw in call.keywords)
                or any(looks_like_dtype(a) for a in call.args))

    out = []
    jitted, _, _ = _jitted_functions(tree)
    seen: set[int] = set()
    for fn in jitted.values():
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                out.append(_raw(
                    "GC-DTYPE", node,
                    f"{_dotted(node) or 'float64'} inside the jitted body "
                    f"{fn.name!r}: the dtype policy is f32/bf16 — under "
                    "x64 an f64 leaf doubles HBM bytes on the exact "
                    "memory-bound paths the roofline ledger budgets "
                    "(AUDIT_LEDGER.json); the graftaudit GA-F64 gate "
                    "fails on the compiled program (CHANGES.md PR 8).",
                ))
            elif (isinstance(node, ast.keyword) and node.arg == "dtype"
                    and isinstance(node.value, ast.Constant)
                    and node.value.value in ("float64", "f64")):
                out.append(_raw(
                    "GC-DTYPE", node.value,
                    f"dtype={node.value.value!r} inside the jitted body "
                    f"{fn.name!r}: the dtype policy is f32/bf16 "
                    "(graftaudit GA-F64 proves it on the compiled "
                    "program; CHANGES.md PR 8).",
                ))
            elif isinstance(node, ast.Call):
                d = _dotted(node.func)
                if (d.split(".")[0] in ("np", "numpy")
                        and _tail(d) in _NP_F64_DEFAULT
                        and not supplies_dtype(node)):
                    out.append(_raw(
                        "GC-DTYPE", node,
                        f"dtype-less {d}(...) inside the jitted body "
                        f"{fn.name!r}: numpy constructors default to "
                        "float64, which traces as an f64 constant under "
                        "x64 — pass dtype=np.float32 (or build with jnp, "
                        "whose weak-typed default stays f32); the "
                        "graftaudit GA-F64 gate fails on the compiled "
                        "program (CHANGES.md PR 8).",
                    ))
    return out


def _check_jsonfinite(tree: ast.Module) -> list[RawFinding]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d not in ("json.dump", "json.dumps"):
            continue
        strict = any(
            kw.arg == "allow_nan"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
            for kw in node.keywords
        )
        guarded = False
        if node.args:
            payload = node.args[0]
            if isinstance(payload, ast.Call):
                fname = _tail(_dotted(payload.func)).lower()
                guarded = any(g in fname for g in _FINITE_GUARDS)
        if not strict and not guarded:
            out.append(_raw(
                "GC-JSONFINITE", node,
                f"{d}(...) without the non-finite guard: a NaN/inf float "
                "serializes as a bare NaN/Infinity token — invalid "
                "strict JSON that breaks jq/pandas/non-Python consumers "
                "(the PR-6 metrics_live.jsonl incident, CHANGES.md "
                "PR 6). Wrap the payload in jsonfinite(...) "
                "(observe/metrics_io.py) to map non-finite -> null, or "
                "pass allow_nan=False to fail loudly on data that must "
                "be finite.",
            ))
    return out


def check_module(tree: ast.Module, path: str) -> list[RawFinding]:
    """Run every rule over one parsed module."""
    out: list[RawFinding] = []
    out += _check_alias(tree)
    out += _check_hostcall(tree, path)
    out += _check_recompile(tree)
    out += _check_thread(tree)
    out += _check_lockshare(tree)
    out += _check_blocking(tree)
    out += _check_dtype(tree)
    out += _check_jsonfinite(tree)
    out.sort(key=lambda f: (f.line, f.rule))
    return out
