#!/usr/bin/env python
"""Fleet serving entrypoint (cgnn_tpu.fleet; ISSUE 14).

Boots N independent serve.py replica processes against one checkpoint
directory, fronts them with a health-routed resilient router (bounded
retries + backoff, deadline-aware hedging, per-replica circuit
breakers, 503 + Retry-After load shedding), and serves the same
``POST /predict`` wire protocol a single replica does — plus
``GET /healthz`` (fleet readiness), ``GET /stats``, ``GET /metrics``
(router counters + per-replica gauges/series), ``GET /metrics/fleet``
(replica histogram families scraped and MERGED into one fleet-wide
exposition — ISSUE 16), and ``GET /timeseries`` (the router's embedded
multi-resolution history).

The replicas share the checkpoint directory, so a rolling promotion is
just the trainer committing a new save: every replica's own hot-reload
watcher picks it up within its poll interval, swapping atomically
mid-load — old and new ``param_version`` serve fleet-wide with zero
drops, exactly like the single-process invariant, now N-wide.

SIGTERM/SIGINT drains: the router sheds new work, replicas get SIGTERM
(their own graceful drain answers queued requests), exit 0.

Usage:
    python fleet.py CKPT_DIR --replicas 3 [--port 8440] ...
"""

from __future__ import annotations

import argparse
import os
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("ckpt_dir", help="checkpoint directory written by train.py")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8440,
                   help="router listen port")
    p.add_argument("--replicas", type=int, default=3,
                   help="serve.py replica processes to boot")
    p.add_argument("--replica-base-port", type=int, default=8441,
                   help="replicas bind base..base+N-1")
    p.add_argument("--log-dir", default="",
                   help="per-replica log files ('' = discard)")
    p.add_argument("--retries", type=int, default=3,
                   help="max extra attempts per request (attempt budget "
                        "= retries + 1, shared with the hedge)")
    p.add_argument("--backoff-ms", type=float, default=25.0,
                   help="initial retry backoff (exponential, jittered)")
    p.add_argument("--hedge-ms", type=float, default=None,
                   help="hedge a request to a second replica after this "
                        "long in flight (default: auto, 2x the "
                        "replica's rolling p99; 0 disables)")
    p.add_argument("--breaker-k", type=int, default=3,
                   help="consecutive failures that eject a replica")
    p.add_argument("--breaker-cooldown", type=float, default=2.0,
                   help="seconds ejected before the half-open probe")
    p.add_argument("--health-interval", type=float, default=1.0,
                   help="seconds between /healthz + /metrics probe rounds")
    p.add_argument("--timeout-ms", type=float, default=30000.0,
                   help="default per-request fleet deadline")
    p.add_argument("--no-feasibility", action="store_true",
                   help="disable deadline-feasibility admission (the "
                        "scraped-p99/queue-depth gate that sheds "
                        "requests whose deadline cannot be met with "
                        "429/504 + Retry-After before any attempt "
                        "crosses a process boundary)")
    p.add_argument("--feasibility-margin", type=float, default=1.0,
                   help="scale the feasibility estimate: shed only when "
                        "predicted completion exceeds deadline x margin "
                        "(>1 = more headroom before shedding)")
    p.add_argument("--drain-timeout", type=float, default=60.0,
                   help="bound on the SIGTERM graceful drain of the "
                        "replica fleet; past it, replicas are killed "
                        "and the router exits non-zero")
    p.add_argument("--serve-arg", action="append", default=[],
                   metavar="ARG", help="extra argument passed through to "
                                       "every serve.py replica "
                                       "(repeatable)")
    # ---- self-driving fleet (ISSUE 17) ----
    p.add_argument("--autoscale", action="store_true",
                   help="close the control loop: grow/shrink the "
                        "routed replica set against the scraped signal "
                        "plane (queue depth, p99 vs SLO, burn rates, "
                        "shed) with hysteresis + cooldowns; drained "
                        "exits are scale events, never incidents")
    p.add_argument("--min-replicas", type=int, default=1,
                   help="autoscaler lower bound on the routed set")
    p.add_argument("--max-replicas", type=int, default=8,
                   help="autoscaler upper bound on the routed set")
    p.add_argument("--warm-pool", type=int, default=1,
                   help="spare replicas kept booted + warm()-compiled "
                        "but unrouted, so scale-up is a routing-table "
                        "add instead of a multi-second warmup")
    p.add_argument("--remediate", action="store_true",
                   help="auto-remediation (needs --flightrec-dir): "
                        "subscribe to flight-recorder triggers and "
                        "replace-and-drain wedged replicas, every "
                        "action journaled to remediation.jsonl naming "
                        "its evidence bundle")
    p.add_argument("--trace-ring", type=int, default=65536, metavar="N",
                   help="router span ring behind GET /trace (+ the "
                        "on-demand fleet join GET /trace/joined); "
                        "0 disables")
    p.add_argument("--trace-out", default="", metavar="PATH",
                   help="write ONE joined fleet trace (router + every "
                        "reachable replica's /trace window) here at "
                        "drain — open it in Perfetto")
    p.add_argument("--flightrec-dir", default="", metavar="DIR",
                   help="incident flight-recorder bundles (joined "
                        "trace + per-process request rings + metrics) "
                        "land here; triggers: replica breaker trip, "
                        "5xx burst ('' disables)")
    p.add_argument("--log-json", action="store_true",
                   help="structured JSON log lines (role + pid + "
                        "current trace id); also passed to every "
                        "replica")
    # ---- fleet SLO engine (ISSUE 16) ----
    p.add_argument("--no-slo", action="store_true",
                   help="disable the fleet SLO engine, the mergeable "
                        "histogram families, and the embedded "
                        "time-series store (the A/B baseline)")
    p.add_argument("--slo-target", type=float, default=0.999,
                   help="fleet availability objective (fraction of "
                        "attempts that must succeed)")
    p.add_argument("--slo-latency-ms", type=float, default=2000.0,
                   help="latency objective threshold: 95%% of answered "
                        "attempts must land under this")
    p.add_argument("--slo-window", type=float, default=300.0,
                   help="error-budget accounting window (seconds)")
    p.add_argument("--slo-fast-s", type=float, default=None,
                   help="burn-rate rule override: fast window seconds "
                        "(default: the two standard pairs scaled to "
                        "--slo-window; set BOTH --slo-fast-s and "
                        "--slo-slow-s to override)")
    p.add_argument("--slo-slow-s", type=float, default=None,
                   help="burn-rate rule override: slow window seconds")
    p.add_argument("--slo-factor", type=float, default=6.0,
                   help="burn-rate rule override: burn factor both "
                        "windows must exceed")
    p.add_argument("--slo-for-s", type=float, default=0.0,
                   help="burn-rate rule override: hold time before "
                        "pending becomes firing")
    # ---- closed-loop continual learning (ISSUE 18) ----
    p.add_argument("--journal", default="", metavar="PATH",
                   help="label journal JSONL: every answered /predict "
                        "is journaled and POST /label joins late "
                        "ground truth by trace id, exactly once — the "
                        "continual trainer's replay feed ('' disables)")
    p.add_argument("--canary", action="store_true",
                   help="canary-gate trainer commits (needs --journal): "
                        "replicas boot reload-GATED at their boot "
                        "version, each new committed candidate is "
                        "pinned to one canary replica, shadow-evaluated "
                        "on mirrored labeled traffic, and only a "
                        "passing candidate promotes fleet-wide "
                        "(rolling, zero downtime); failures roll back "
                        "with a flight-recorder bundle naming the "
                        "version")
    p.add_argument("--canary-mirror", type=float, default=1.0,
                   help="fraction of labeled live traffic mirrored to "
                        "the canary (0, 1]")
    p.add_argument("--canary-min-samples", type=int, default=50,
                   help="labeled shadow mirrors required for a verdict")
    p.add_argument("--canary-max-mae-ratio", type=float, default=1.05,
                   help="promote when shadow/live MAE ratio <= this")
    p.add_argument("--canary-rollback-mae-ratio", type=float,
                   default=1.25,
                   help="roll back when the MAE ratio >= this")
    p.add_argument("--canary-p99-ms", type=float, default=2000.0,
                   help="shadow p99 budget; above it the candidate "
                        "rolls back on latency")
    p.add_argument("--canary-window", type=float, default=300.0,
                   help="max seconds a candidate may stay undecided "
                        "before it rolls back (window_expired)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from cgnn_tpu.fleet.http import make_fleet_http_server
    from cgnn_tpu.fleet.replica import ReplicaState
    from cgnn_tpu.fleet.router import FleetRouter
    from cgnn_tpu.fleet.spawn import spawn_fleet
    from cgnn_tpu.observe import json_log_fn
    from cgnn_tpu.resilience.preempt import PreemptionHandler

    log = json_log_fn("router") if args.log_json else print

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    log(f"fleet: booting {args.replicas} replicas on ports "
        f"{args.replica_base_port}.."
        f"{args.replica_base_port + args.replicas - 1} "
        f"(ckpt {args.ckpt_dir})")
    serve_args = list(args.serve_arg)
    if args.log_json:
        serve_args.append("--log-json")
    if args.canary and not args.journal:
        print("fleet: --canary needs --journal (the gate evaluates "
              "labeled live traffic)", file=sys.stderr)
        return 2
    if args.canary:
        # every replica (boot fleet, autoscaled adds, warm spares)
        # holds its reload gate at its boot version: trainer commits
        # are CANDIDATES until the canary controller promotes them
        serve_args.append("--reload-gated")
    try:
        procs = spawn_fleet(
            args.ckpt_dir, args.replicas,
            base_port=args.replica_base_port, host=args.host,
            log_dir=args.log_dir or None, serve_args=serve_args,
        )
    except (RuntimeError, FileNotFoundError) as e:
        print(str(e), file=sys.stderr)
        return 2

    replicas = [
        ReplicaState(p.rid, p.base_url, breaker_k=args.breaker_k,
                     breaker_cooldown_s=args.breaker_cooldown)
        for p in procs
    ]
    # fleet SLO engine (ISSUE 16): objectives from the flags; burn-rate
    # rules default to the standard pairs scaled to the window, with a
    # single-rule override for second-scale windows (the smoke legs)
    slo_objectives = slo_rules = None
    if not args.no_slo:
        from cgnn_tpu.observe.slo import BurnRateRule, SLOObjective

        slo_objectives = (
            SLOObjective("fleet_availability", target=args.slo_target,
                         window_s=args.slo_window),
            SLOObjective("fleet_latency", target=0.95,
                         latency_threshold_ms=args.slo_latency_ms,
                         window_s=args.slo_window),
        )
        if args.slo_fast_s is not None and args.slo_slow_s is not None:
            slo_rules = (BurnRateRule(
                fast_s=args.slo_fast_s, slow_s=args.slo_slow_s,
                factor=args.slo_factor, for_s=args.slo_for_s),)
    router = FleetRouter(
        replicas,
        max_attempts=args.retries + 1,
        backoff_ms=args.backoff_ms,
        hedge_ms=args.hedge_ms,
        default_timeout_ms=args.timeout_ms,
        feasibility=not args.no_feasibility,
        feasibility_margin=args.feasibility_margin,
        health_interval_s=args.health_interval,
        trace_ring=args.trace_ring,
        slo_layer=not args.no_slo,
        slo_objectives=slo_objectives,
        slo_rules=slo_rules,
        log_fn=log,
    ).start()

    if args.flightrec_dir:
        from cgnn_tpu.observe import FlightRecorder

        router.attach_flight_recorder(FlightRecorder(
            args.flightrec_dir, role="router",
            name=f"router:{args.port}",
            registry=router.registry, tracer=router.tracer,
            peers=router.replica_trace_urls(),
            manifest={"ckpt_dir": args.ckpt_dir,
                      "replicas": args.replicas},
            log_fn=log,
        ))

    # ---- the self-driving layers (ISSUE 17) ----
    autoscaler = None
    if args.autoscale or args.remediate:
        from cgnn_tpu.fleet.autoscale import AutoscalePolicy, Autoscaler
        from cgnn_tpu.fleet.spawn import ReplicaProcess

        def _proc_factory(rid: int) -> ReplicaProcess:
            log_path = (os.path.join(args.log_dir, f"replica-{rid}.log")
                        if args.log_dir else None)
            return ReplicaProcess(
                rid, args.ckpt_dir, args.replica_base_port + rid,
                host=args.host, log_path=log_path,
                serve_args=serve_args)

        def _state_factory(rid: int, base_url: str) -> ReplicaState:
            return ReplicaState(
                rid, base_url, breaker_k=args.breaker_k,
                breaker_cooldown_s=args.breaker_cooldown)

        autoscaler = Autoscaler(
            router,
            AutoscalePolicy(min_replicas=args.min_replicas,
                            max_replicas=args.max_replicas,
                            warm_target=args.warm_pool if args.autoscale
                            else 0),
            _proc_factory, _state_factory,
            # seed ownership with the boot fleet so scale-down can
            # drain and reap the initial replicas too
            procs={p.rid: p for p in procs}, next_rid=args.replicas,
            poll_interval_s=max(args.health_interval, 0.25),
            drain_timeout_s=args.drain_timeout, log_fn=log,
        )
        router.autoscaler = autoscaler
        if args.autoscale:
            # without --autoscale the instance is just the process
            # machinery the remediator replaces through (no loop)
            autoscaler.start()

    remediator = None
    if args.remediate:
        if router.flightrec is None:
            print("fleet: --remediate needs --flightrec-dir (the "
                  "remediator consumes flight-recorder triggers)",
                  file=sys.stderr)
            return 2
        from cgnn_tpu.fleet.remediate import Remediator

        remediator = Remediator(
            router, autoscaler,
            out_dir=args.flightrec_dir,
            drain_timeout_s=args.drain_timeout, log_fn=log,
        ).attach(router.flightrec)
        router.remediator = remediator

    # ---- closed-loop continual learning (ISSUE 18) ----
    journal = None
    canary_ctl = None
    if args.journal:
        from cgnn_tpu.continual import LabelJournal

        journal = LabelJournal(args.journal)
        router.attach_journal(journal)
        log(f"fleet: label journal -> {args.journal} (POST /label "
            "joins ground truth)")
    if args.canary:
        from cgnn_tpu.continual import (
            CanaryController,
            CanaryGate,
            GateConfig,
        )
        from cgnn_tpu.train import CheckpointManager

        canary_mgr = CheckpointManager(args.ckpt_dir)
        canary_ctl = CanaryController(
            gate=CanaryGate(GateConfig(
                min_samples=args.canary_min_samples,
                min_baseline=args.canary_min_samples,
                max_mae_ratio=args.canary_max_mae_ratio,
                rollback_mae_ratio=args.canary_rollback_mae_ratio,
                p99_budget_ms=args.canary_p99_ms,
                max_window_s=args.canary_window,
            )),
            journal=journal, fleet=router,
            newest_fn=canary_mgr.newest_committed,
            mirror_fraction=args.canary_mirror,
            flightrec=router.flightrec, log_fn=log,
        )
        router.attach_canary(canary_ctl)
        canary_ctl.start()
        log("fleet: canary gate armed (replicas reload-gated; trainer "
            "commits shadow-evaluate before fleet-wide promotion)")

    httpd = make_fleet_http_server(router, host=args.host, port=args.port)
    stop = threading.Event()
    handler = PreemptionHandler(
        log_fn=log,
        action="draining the fleet (router sheds new work; replicas "
               "drain their queues)",
    )
    handler.add_callback(stop.set)
    handler.install()

    listener = threading.Thread(target=httpd.serve_forever, daemon=True,
                                name="fleet-http")
    listener.start()
    log(f"fleet: routing on http://{args.host}:{args.port} over "
        f"{len(replicas)} replicas "
        f"({router.ready_count()} ready; live plane: GET /metrics"
        + (", GET /trace/joined" if router.tracer is not None else "")
        + ")")
    try:
        while not stop.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    httpd.shutdown()
    httpd.server_close()
    if canary_ctl is not None:
        canary_ctl.stop()
        canary_mgr.close()
    router.stop()
    if journal is not None:
        journal.close()
    if args.trace_out and router.tracer is not None:
        # one joined Perfetto file for the whole run: the router's ring
        # plus every still-reachable replica's /trace window (pulled
        # BEFORE the replicas drain away)
        from cgnn_tpu.observe import trace_join

        windows, errors = trace_join.collect_windows(
            router.replica_trace_urls())
        doc = trace_join.write_joined(
            args.trace_out, [router.trace_window(), *windows])
        log(f"fleet: joined trace -> {args.trace_out} "
            f"({1 + len(windows)} process(es), "
            f"{len(doc['traces'])} trace(s)"
            + (f"; unreachable: {sorted(errors)}" if errors else "")
            + ")")
    if remediator is not None:
        remediator.stop()
    if autoscaler is not None:
        # drains EVERYTHING the autoscaler owns: the boot fleet it was
        # seeded with, scaled-up replicas, and warm-pool spares
        codes = list(autoscaler.shutdown(
            drain_timeout_s=args.drain_timeout).values())
    else:
        codes = [p.terminate(timeout_s=args.drain_timeout) for p in procs]
    handler.uninstall()
    if router.flightrec is not None:
        router.flightrec.wait_idle(timeout_s=15.0)
    stats = router.stats()["counts"]
    log(f"fleet: drained — {stats['fleet_answered']} answered, "
        f"{stats['fleet_retries']} retries, {stats['fleet_hedges']} "
        f"hedges, {stats['fleet_shed']} shed; "
        f"{stats['fleet_scale_events']} scale events, "
        f"{stats['fleet_incidents']} incidents; replica exits {codes}")
    # the PR-2 resumable code 75 is a PREEMPTION, not a failure: a
    # drained exit-75 replica left cleanly (the scale-event contract)
    bad = [c for c in codes if c not in (0, 75)]
    if bad:
        print(f"fleet: replica drain failures: {codes}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
