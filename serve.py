#!/usr/bin/env python
"""Online inference server entrypoint (cgnn_tpu.serve; ISSUE 3).

Loads a train.py checkpoint, plans + warms the fixed serving shape set,
starts the hot-reload watcher on the checkpoint directory, and serves
HTTP until SIGTERM/SIGINT — which triggers a graceful drain (queued
requests answered, new ones rejected 503) and exit 0.

Usage:
    python serve.py CKPT_DIR [--port 8437] [--batch-size 64] ...
"""

from __future__ import annotations

import argparse
import os
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("ckpt_dir", help="checkpoint directory written by train.py")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8437)
    p.add_argument("--device", choices=["auto", "cpu", "tpu"], default="auto")
    p.add_argument("-b", "--batch-size", type=int, default=64,
                   help="graph budget of the largest serving shape")
    p.add_argument("--rungs", type=int, default=3,
                   help="shape-ladder depth (compile count at warmup)")
    p.add_argument("--max-wait-ms", type=float, default=5.0,
                   help="micro-batch flush deadline")
    p.add_argument("--class-wait-ms", default="",
                   help="per-priority-class flush budgets, e.g. "
                        "'batch=20,scavenger=80' (ms; unlisted classes "
                        "keep the defaults: interactive=1x, batch=4x, "
                        "scavenger=16x --max-wait-ms)")
    p.add_argument("--no-backfill", action="store_true",
                   help="disable padding-slack backfill (lower-class "
                        "requests riding a higher-class flush's spare "
                        "graph/node/edge slots; the A/B baseline)")
    p.add_argument("--wfq-weights", default="",
                   help="weighted-fair-queuing tenant weights, e.g. "
                        "'acme=4,guest=1' (unlisted tenants weigh 1)")
    p.add_argument("--class-slo-ms", default="",
                   help="per-class p95 latency SLO objectives, e.g. "
                        "'interactive=250,batch=2000' — adds a "
                        "class-scoped latency objective per entry")
    p.add_argument("--max-queue", type=int, default=256,
                   help="admission bound (backpressure: reject above this)")
    p.add_argument("--timeout-ms", type=float, default=1000.0,
                   help="default per-request deadline (0 disables)")
    p.add_argument("--cache-size", type=int, default=1024,
                   help="LRU result cache entries (0 disables)")
    p.add_argument("--compact", choices=["auto", "on", "off"],
                   default="auto",
                   help="compact-staged serving (data/compact.py): auto "
                        "engages on accelerator backends, on/off force")
    p.add_argument("--wire", choices=["auto", "raw", "featurized"],
                   default="auto",
                   help="raw-wire serving (ISSUE 11): 'raw' admits "
                        "(positions, lattice, species) structure "
                        "payloads straight into a warmed in-program "
                        "neighbor-search + featurize program (~100x "
                        "fewer request bytes, near-zero host work; "
                        "structures outside the raw rung caps fall "
                        "back to pack-pool featurization); 'auto' "
                        "engages on accelerator backends")
    p.add_argument("--pack-workers", type=int, default=None,
                   help="pack pipeline threads between batcher and "
                        "dispatch (0 = in-line; default follows the "
                        "backend like --compact auto)")
    p.add_argument("--precision", default="f32", metavar="TIERS",
                   help="comma-separated precision tiers to warm "
                        "(f32,bf16,int8 — serve/quantize.py); requests "
                        "pick a tier per call via the 'precision' field "
                        "(default f32). Every tier is compiled at warmup "
                        "for every rung — zero recompiles after")
    p.add_argument("--devices", default="auto", metavar="{auto,N}",
                   help="device-parallel dispatch set (serve/devices.py): "
                        "'auto' = all local devices on accelerator "
                        "backends, one on CPU; an integer forces that "
                        "many anywhere (the 8-host-device dryrun)")
    p.add_argument("--engine", choices=["auto", "mesh", "threads"],
                   default="auto",
                   help="multi-device execution layer (ISSUE 10): 'mesh' "
                        "(the auto default with >1 device) batch-shards "
                        "each flush over a Mesh+NamedSharding layout and "
                        "ONE jitted dispatch covers all devices — compile "
                        "count = programs, one sharded param tree; "
                        "'threads' keeps the per-device dispatch-thread "
                        "DeviceSet layer (the A/B baseline)")
    p.add_argument("--poll-interval", type=float, default=2.0,
                   help="hot-reload checkpoint poll seconds (0 disables)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="bound on the SIGTERM graceful drain: past it "
                        "the process force-exits non-zero with the "
                        "unanswered count logged (a wedged flush must "
                        "not hold shutdown forever)")
    p.add_argument("--drain-linger", type=float, default=0.0,
                   help="after a clean drain, keep answering /healthz "
                        "(draining=true) for this many seconds before "
                        "exiting — set it >= the fleet health-probe "
                        "interval so the router OBSERVES the draining "
                        "state and classifies the exit as a scale "
                        "event, not an incident (ISSUE 17)")
    p.add_argument("--calibrate", type=int, default=256,
                   help="synthetic calibration structures for shape planning")
    p.add_argument("--calibration-cache", type=str, default="",
                   help="featurized graph cache to calibrate shapes from "
                        "(real traffic distribution beats synthetic)")
    p.add_argument("--telemetry-dir", type=str, default="",
                   help="write serving metrics.jsonl here ('' disables)")
    p.add_argument("--live-metrics", type=float, default=0.0, metavar="SECS",
                   help="append a registry snapshot (counters/gauges/"
                        "rolling quantiles) to metrics_live.jsonl every "
                        "SECS seconds (0 disables); the same live view "
                        "GET /metrics serves in Prometheus format")
    p.add_argument("--profile-dir", type=str, default="auto",
                   help="where POST /profile and SIGUSR2 write bounded "
                        "on-demand jax.profiler captures ('auto' = the "
                        "telemetry dir when set, else CKPT_DIR/profiles; "
                        "'' disables)")
    p.add_argument("--compile-cache", type=str, default="/tmp/jax_cache",
                   metavar="DIR", help="persistent XLA compile cache "
                                       "('' disables; warm restarts replay "
                                       "compiles from disk)")
    p.add_argument("--trace-ring", type=int, default=65536, metavar="N",
                   help="bounded always-on serving span ring behind "
                        "GET /trace — the fleet trace-join surface "
                        "(ISSUE 15); 0 disables (the PERF.md §18 A/B "
                        "baseline)")
    p.add_argument("--flightrec-dir", type=str, default="auto",
                   help="incident flight-recorder bundles land here "
                        "('auto' = the telemetry dir when set, else "
                        "CKPT_DIR/flightrec; '' disables). Triggers: "
                        "5xx burst, drain force-exit, racecheck "
                        "watchdog")
    p.add_argument("--log-json", action="store_true",
                   help="structured JSON log lines (role + pid + "
                        "current trace id per line) instead of plain "
                        "prints — bundle logs then grep by trace id")
    # ---- SLO engine + metrics truth (ISSUE 16) ----
    p.add_argument("--no-slo", action="store_true",
                   help="disable the SLO engine, the mergeable "
                        "histogram families, and the embedded "
                        "time-series store (the A/B baseline)")
    p.add_argument("--slo-target", type=float, default=0.999,
                   help="availability objective (fraction of requests "
                        "that must be answered)")
    p.add_argument("--slo-latency-ms", type=float, default=1000.0,
                   help="latency objective threshold: 95%% of answers "
                        "must land under this")
    p.add_argument("--slo-window", type=float, default=300.0,
                   help="error-budget accounting window (seconds)")
    p.add_argument("--slo-fast-s", type=float, default=None,
                   help="burn-rate rule override: fast window seconds "
                        "(set BOTH --slo-fast-s and --slo-slow-s; "
                        "default: the standard pairs scaled to "
                        "--slo-window)")
    p.add_argument("--slo-slow-s", type=float, default=None,
                   help="burn-rate rule override: slow window seconds")
    p.add_argument("--slo-factor", type=float, default=6.0,
                   help="burn-rate rule override: burn factor")
    p.add_argument("--slo-for-s", type=float, default=0.0,
                   help="burn-rate rule override: hold time before "
                        "pending becomes firing")
    p.add_argument("--journal", type=str, default="",
                   help="label journal JSONL path (ISSUE 18): every "
                        "served response is journaled and POST /label "
                        "joins late ground truth by trace id or "
                        "fingerprint, exactly once")
    p.add_argument("--reload-gated", action="store_true",
                   help="hold the reload watcher's auto-swap at the "
                        "boot version (continual/canary plane): newer "
                        "checkpoints are CANDIDATES until a POST "
                        "/reload-control raises the gate")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.device == "cpu":
        # env var alone is not honored under the axon TPU tunnel
        jax.config.update("jax_platforms", "cpu")
    if args.compile_cache:
        try:
            jax.config.update("jax_compilation_cache_dir", args.compile_cache)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
        except Exception as e:  # noqa: BLE001 — cache is best-effort
            print(f"compilation cache unavailable: {e}", file=sys.stderr)

    from cgnn_tpu.observe import Telemetry, json_log_fn
    from cgnn_tpu.serve.http import make_http_server
    from cgnn_tpu.serve.batcher import parse_kv_spec
    from cgnn_tpu.serve.server import load_server

    # one logging sink for everything this process prints: JSON lines
    # (role/pid/trace id) under --log-json, plain print otherwise
    log = json_log_fn("replica") if args.log_json else print

    telemetry = (
        Telemetry(level="epoch", log_dir=args.telemetry_dir)
        if args.telemetry_dir else Telemetry.disabled()
    )
    calibration = None
    if args.calibration_cache:
        from cgnn_tpu.data.cache import load_graph_cache

        calibration = load_graph_cache(args.calibration_cache)
    profile_dir = args.profile_dir
    if profile_dir == "auto":
        profile_dir = args.telemetry_dir or os.path.join(
            args.ckpt_dir, "profiles")
    # SLO engine (ISSUE 16): objectives from the flags; rules default to
    # the standard pairs scaled to the window unless both --slo-fast-s
    # and --slo-slow-s override (second-scale windows for smoke tests)
    slo_objectives = slo_rules = None
    if not args.no_slo:
        from cgnn_tpu.observe.slo import BurnRateRule, SLOObjective

        slo_objectives = (
            SLOObjective("availability", target=args.slo_target,
                         window_s=args.slo_window),
            SLOObjective("latency", target=0.95,
                         latency_threshold_ms=args.slo_latency_ms,
                         window_s=args.slo_window),
        )
        if args.class_slo_ms:
            # class-scoped objectives (ISSUE 19): only events of the
            # matching priority class feed these windows, so a slow
            # scavenger backlog cannot burn the interactive budget
            slo_objectives += tuple(
                SLOObjective(f"latency_{kl}", target=0.95,
                             latency_threshold_ms=ms,
                             window_s=args.slo_window, klass=kl)
                for kl, ms in parse_kv_spec(args.class_slo_ms).items()
            )
        if args.slo_fast_s is not None and args.slo_slow_s is not None:
            slo_rules = (BurnRateRule(
                fast_s=args.slo_fast_s, slow_s=args.slo_slow_s,
                factor=args.slo_factor, for_s=args.slo_for_s),)
    try:
        server, parts = load_server(
            args.ckpt_dir,
            batch_size=args.batch_size,
            rungs=args.rungs,
            calibration=calibration,
            calibration_n=args.calibrate,
            telemetry=telemetry,
            max_queue=args.max_queue,
            max_wait_ms=args.max_wait_ms,
            class_max_wait_ms=(parse_kv_spec(args.class_wait_ms)
                               if args.class_wait_ms else None),
            backfill=not args.no_backfill,
            wfq_weights=(parse_kv_spec(args.wfq_weights)
                         if args.wfq_weights else None),
            default_timeout_ms=args.timeout_ms or None,
            cache_size=args.cache_size,
            compact=args.compact,
            wire=args.wire,
            pack_workers=args.pack_workers,
            devices=args.devices,
            engine=args.engine,
            precision=args.precision,
            watch=args.poll_interval > 0,
            # warm AFTER the listener binds (below): /healthz answers
            # ready=False during compilation instead of refusing
            # connections, so a fleet router can tell warming from dead
            warm=False,
            poll_interval_s=args.poll_interval or 2.0,
            profile_dir=profile_dir,
            trace_ring=args.trace_ring,
            slo_layer=not args.no_slo,
            slo_objectives=slo_objectives,
            slo_rules=slo_rules,
            log_fn=log,
        )
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2

    # incident flight recorder (ISSUE 15; observe/flightrec.py): the
    # always-cheap per-request ring + metrics/trace bundle dumps on
    # trigger — 5xx bursts (fed by the HTTP layer), the bounded-drain
    # force exit below, and the racecheck watchdog when that gate is on
    recorder = None
    flightrec_dir = args.flightrec_dir
    if flightrec_dir == "auto":
        flightrec_dir = args.telemetry_dir or os.path.join(
            args.ckpt_dir, "flightrec")
    if flightrec_dir:
        from cgnn_tpu.observe import FlightRecorder

        recorder = FlightRecorder(
            flightrec_dir, role="replica",
            name=f"replica:{args.port}",
            registry=server.registry, tracer=server.tracer,
            manifest={
                "ckpt_dir": args.ckpt_dir,
                "param_version": server.param_store.version,
                "port": args.port,
                "engine": server.engine,
                "precisions": list(server.precisions),
            },
            log_fn=log,
        )
        server.attach_flight_recorder(recorder)

    # continual-learning plane (ISSUE 18): the label journal joins
    # late ground truth onto served responses; --reload-gated turns
    # newer checkpoints into held CANDIDATES until the canary
    # controller's promotion broadcast raises the gate
    journal = None
    if args.journal:
        from cgnn_tpu.continual import LabelJournal

        journal = LabelJournal(args.journal)
        server.attach_journal(journal)
    if args.reload_gated and server.watcher is not None:
        server.watcher.set_gate(server.param_store.version)
        log(f"reload gate held at boot version "
            f"{server.param_store.version} (POST /reload-control to "
            "promote)")

    # the live plane's two push/pull surfaces beyond HTTP: SIGUSR2 ->
    # bounded on-demand device profile; --live-metrics -> periodic
    # registry snapshots for fleets scraped by file instead of port
    if server.profiler is not None:
        from cgnn_tpu.observe import install_sigusr2

        install_sigusr2(server.profiler, log_fn=log)
    live_writer = None
    if args.live_metrics > 0:
        from cgnn_tpu.observe import LiveMetricsWriter

        live_writer = LiveMetricsWriter(
            server.registry,
            os.path.join(args.telemetry_dir or args.ckpt_dir,
                         "metrics_live.jsonl"),
            interval_s=args.live_metrics,
        ).start()

    # no handler-side featurizer: wire-form structures admit directly
    # and the SERVER featurizes on the pack pool when needed (ISSUE 11)
    httpd = make_http_server(server, host=args.host, port=args.port)

    # SIGTERM/SIGINT -> drain the batcher, stop the listener, exit
    # (resilience.preempt signal plumbing; second signal kills)
    stop = threading.Event()
    handler = server.install_signal_handlers()
    handler.add_callback(stop.set)

    # bind + listen BEFORE warm (ISSUE 14 readiness): /healthz reports
    # ready=False (503) while the shape set compiles, flipping to 200
    # the moment warm() finishes — the router's admission signal
    listener = threading.Thread(target=httpd.serve_forever, daemon=True,
                                name="http-listener")
    listener.start()
    log(f"listening on http://{args.host}:{args.port} "
        f"(warming {len(server.shape_set)} shapes; "
        f"/healthz reports ready=false until done)")
    # fleet boot fault point (ISSUE 17): the listener is bound, warm()
    # has not run — where boot_crash dies and wedge_warm hangs
    from cgnn_tpu.resilience import faultinject

    faultinject.boot_point()
    server.warm(parts["template"])
    server.start()
    if recorder is not None:
        from cgnn_tpu.analysis import racecheck

        if racecheck.enabled():
            # a deadlock-watchdog dump is exactly the incident the
            # recorder exists for: re-arm the singleton's log hook so
            # the stall report also dumps a bundle (server.start()
            # armed it with the plain server log a moment ago)
            def _watchdog_log(msg):
                log(msg)
                recorder.trigger("watchdog", str(msg))

            racecheck.start_watchdog(bound_s=30.0, log_fn=_watchdog_log)

    shapes = ", ".join(
        f"({s.graph_cap}g/{s.node_cap}n/{s.edge_cap}e)"
        for s in server.shape_set
    )
    log(f"serving on http://{args.host}:{args.port} "
        f"(params {server.param_store.version}; shapes {shapes}; "
        f"{len(server.device_set)} device(s), {server.engine} engine; "
        f"wire: "
        f"{'raw+featurized' if server.shape_set.raw is not None else 'featurized'}; "
        f"live plane: GET /metrics"
        + (", GET /trace" if server.tracer is not None else "")
        + (f", flightrec -> {flightrec_dir}" if recorder else "")
        + (f", POST /profile -> {profile_dir}" if profile_dir else "")
        + ")")
    try:
        while not stop.wait(0.5):
            pass
    except KeyboardInterrupt:
        server.begin_drain()
    # drain with the LISTENER STILL UP (ISSUE 17): /healthz keeps
    # answering draining=true (new /predict requests get the typed 503
    # rejection), so the fleet health poller can observe the planned
    # exit and classify it a scale event instead of an incident. The
    # listener closes only after the drain (+ optional linger) ends.
    clean = server.drain(timeout_s=args.drain_timeout)
    if clean and args.drain_linger > 0:
        import time as _time

        _time.sleep(args.drain_linger)
    httpd.shutdown()
    httpd.server_close()
    handler.uninstall()
    if live_writer is not None:
        live_writer.stop()
    if journal is not None:
        journal.close()
    stats = server.stats()
    lat = stats["latency_ms"]
    if lat:
        log(f"drained: {stats['counts']['responses']} responses, "
            f"p50 {lat['p50']:.1f} ms / p99 {lat['p99']:.1f} ms")
    telemetry.close()
    if not clean:
        # the bounded-drain satellite (ISSUE 14): a wedged flush must
        # not hold shutdown forever. Log the unanswered count, then
        # FORCE-exit — a daemon worker blocked in a wedged dispatch can
        # pin interpreter teardown, and the supervisor (or the chaos
        # harness) needs this process GONE with a non-zero code.
        c = stats["counts"]
        rejected = sum(v for k, v in c.items() if k.startswith("reject_"))
        unanswered = (c.get("requests", 0) - c.get("responses", 0)
                      - c.get("cache_hits", 0) - rejected)
        if recorder is not None:
            # the flight-recorder trigger for exactly this incident:
            # dump the ring + metrics + trace BEFORE the hard exit.
            # wait=True: os._exit would otherwise race the dump thread
            # and truncate the bundle. force=True: the wedge that
            # caused this drain typically ALSO fired a 5xx/timeout
            # burst moments earlier, and the final bundle must not be
            # rate-limited away by its own symptom.
            recorder.trigger(
                "drain_force_exit",
                f"{max(unanswered, 0)} unanswered after "
                f"{args.drain_timeout:.0f} s drain",
                wait=True, force=True)
        print(f"drain timed out after {args.drain_timeout:.0f} s: "
              f"{max(unanswered, 0)} accepted request(s) unanswered, "
              f"{stats['queue_depth']} still queued; force-exiting 3",
              file=sys.stderr)
        sys.stderr.flush()
        sys.stdout.flush()
        os._exit(3)
    if recorder is not None:
        recorder.wait_idle(timeout_s=10.0)
    if faultinject.exit75_requested():
        # the injected preemption drained cleanly: report it with the
        # PR-2 resumable code, the signature the fleet router records
        # as a scale event rather than an incident
        from cgnn_tpu.resilience import RESUMABLE_EXIT_CODE

        return RESUMABLE_EXIT_CODE
    return 0


if __name__ == "__main__":
    sys.exit(main())
