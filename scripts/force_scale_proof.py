#!/usr/bin/env python
"""Force-task scale proof (BASELINE config #5 at scale; VERDICT r4 #8).

Real MD17 data is unavailable offline, so this exercises the FULL force
pipeline at MD17 scale with synthetic LJ trajectories (the same potential
tests/test_forces.py fits): several independent trajectories of different
molecule sizes, leak-aware whole-trajectory splits, the dense edge-slot
layout with the linear_call two-tier transpose under the second-order
force objective, snug packing, size-class buckets, and the scan epoch
driver — the exact composition `train.py --task force --scan-epochs`
runs. Records the force-MAE convergence curve AND end-to-end throughput
in one artifact (config #2's SCALE_PROOF_MP146K.json, for the force task).

MD17's headline sets are 50k-600k frames of 9-21-atom molecules, with
train/test drawn from the SAME molecule's trajectory — a per-molecule
fit, not cross-molecule transfer. The default here matches that: ONE
long 12-atom LJ trajectory, which the leak-aware splitter divides into
contiguous time blocks (train on early frames, validate/test on later
ones — adjacent-frame leakage excluded by block contiguity).

--trajectories 2 trains 12- and 16-atom systems jointly (time-block
splits per trajectory; exercises size buckets), and >= 3 switches to
whole-trajectory splits (cross-molecule transfer). CAVEAT measured in
this script's own history: mixing molecules makes the energy
distribution multi-modal, so the energy normalizer's std blows up and
the scaled force targets shrink toward zero — the 2-molecule run
converged to a force MAE WORSE than predicting zero force (0.54 vs the
0.22 zero-predictor bound) while the single-molecule default reaches
far below it. Joint multi-molecule training needs per-atom or
per-molecule energy normalization, which the reference lineage does not
have either; the artifact reports the zero-predictor bound so this
failure mode is visible.

Prints one JSON line (FORCE_SCALE_PROOF.json via --out).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--frames", type=int, default=50_000)
    p.add_argument("--trajectories", type=int, default=1)
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--buckets", type=int, default=2)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--lr-milestones", type=int, nargs="*", default=[],
                   metavar="EPOCH",
                   help="epochs at which lr decays 10x (MultiStepLR, like "
                        "train.py; late-training loss spikes under a "
                        "constant Adam lr cap the force-MAE floor)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--device", choices=["auto", "cpu"], default="auto")
    p.add_argument("--no-scan", action="store_true",
                   help="per-step loop instead of the scan epoch driver")
    p.add_argument("--compile-cache", type=str, default="/tmp/jax_cache")
    p.add_argument("--out", default="")
    args = p.parse_args(argv)
    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    compile_cache_warm = False
    if args.compile_cache:
        compile_cache_warm = bool(os.path.isdir(args.compile_cache)
                                  and os.listdir(args.compile_cache))
        jax.config.update("jax_compilation_cache_dir", args.compile_cache)
    import numpy as np

    from cgnn_tpu.data.dataset import FeaturizeConfig, load_trajectory
    from cgnn_tpu.data.trajectory import split_trajectory_groups
    from cgnn_tpu.models.forcefield import ForceFieldCGCNN
    from cgnn_tpu.train import (
        Normalizer,
        create_train_state,
        fit,
        make_optimizer,
    )
    from cgnn_tpu.train.force_step import (
        make_force_eval_step,
        make_force_train_step,
    )

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)

    # ---- stage 1: generate + featurize (timed) ------------------------
    t0 = time.perf_counter()
    per_traj = args.frames // args.trajectories
    sizes = ([12] if args.trajectories == 1
             else [12, 16] if args.trajectories == 2
             else [8 + 2 * (t % 7) for t in range(args.trajectories)])
    groups = []
    for t in range(args.trajectories):
        grp = load_trajectory(per_traj, cfg, seed=100 + t,
                              num_atoms=sizes[t])
        for g in grp:
            g.cif_id = f"traj{t}/{g.cif_id}"
        groups.append(grp)
    featurize_s = time.perf_counter() - t0
    n_frames = sum(len(g) for g in groups)

    # ---- stage 2: leak-aware split (contiguous time blocks within each
    # trajectory below 3 trajectories — incl. the single-molecule
    # default; whole trajectories per split from 3 up — module docstring)
    train_g, val_g, test_g = split_trajectory_groups(
        groups, 0.8, 0.1, seed=args.seed
    )

    # label scale, so the MAE numbers are interpretable: predicting zero
    # force scores ~force_label_mean_abs; a fitted model must land well
    # below it (the multi-molecule normalizer caveat in the docstring was
    # caught by exactly this bound)
    all_f = np.concatenate([g.forces for grp in groups for g in grp])
    all_e = np.array([float(g.target[0]) for grp in groups for g in grp])
    force_label_stats = {
        "mean_abs": round(float(np.abs(all_f).mean()), 4),
        "std": round(float(all_f.std()), 4),
        # the zero-force predictor's MAE on the TEST split — the bound
        # test_force_mae is compared against (same split, same metric)
        "zero_predictor_test_force_mae": round(float(np.abs(
            np.concatenate([g.forces for g in test_g])).mean()), 4),
        "energy_std": round(float(all_e.std()), 4),
    }

    # ---- stage 3: train (end-to-end timed per epoch) ------------------
    model = ForceFieldCGCNN(atom_fea_len=64, n_conv=3, h_fea_len=64,
                            dmin=cfg.dmin, dmax=cfg.radius, step=cfg.step,
                            dense_m=cfg.max_num_nbr)
    normalizer = Normalizer.fit(np.stack([g.target for g in train_g]))

    from cgnn_tpu.data.graph import (
        assign_size_buckets,
        batch_iterator,
        capacities_for,
        count_batches,
    )

    nc, ec = capacities_for(train_g, args.batch_size,
                            dense_m=cfg.max_num_nbr, snug=True)
    # real steps/epoch for milestone->step conversion: fit(buckets=N)
    # batches per size class with per-class snug capacities, so count the
    # same way — a single global count_batches over-/under-counts the
    # per-bucket tails and lands the decay epochs off target
    bucket_of = assign_size_buckets(train_g, args.buckets)
    steps_per_epoch = 0
    for b in range(int(bucket_of.max()) + 1):
        sub = [g for g, bi in zip(train_g, bucket_of) if bi == b]
        if not sub:
            continue
        bnc, bec = capacities_for(sub, args.batch_size,
                                  dense_m=cfg.max_num_nbr, snug=True)
        steps_per_epoch += count_batches(sub, args.batch_size, bnc, bec,
                                         snug=True)
    steps_per_epoch = max(1, steps_per_epoch)
    tx = make_optimizer(
        optim="adam", lr=args.lr,
        lr_milestones=[m * steps_per_epoch for m in args.lr_milestones]
        or [10**9],
    )
    example = next(batch_iterator(train_g, args.batch_size, nc, ec,
                                  dense_m=cfg.max_num_nbr, snug=True))
    state = create_train_state(model, example, tx, normalizer,
                               rng=jax.random.key(args.seed))

    epoch_s: list[float] = []
    curve: list[dict] = []
    last = [time.perf_counter()]

    def on_metrics(epoch, train_m, val_m):
        now = time.perf_counter()
        epoch_s.append(round(now - last[0], 1))
        last[0] = now
        curve.append({
            "epoch": epoch,
            "train_loss": round(float(train_m.get("loss", np.nan)), 5),
            "val_force_mae": round(float(val_m.get("force_mae", np.nan)), 5),
            "val_energy_mae": round(float(val_m.get("mae", np.nan)), 5),
        })

    state, result = fit(
        state, train_g, val_g, epochs=args.epochs,
        batch_size=args.batch_size, node_cap=nc, edge_cap=ec,
        seed=args.seed, print_freq=0,
        train_step_fn=make_force_train_step(),
        eval_step_fn=make_force_eval_step(),
        best_metric="force_mae", buckets=args.buckets, snug=True,
        dense_m=cfg.max_num_nbr, scan_epochs=not args.no_scan,
        on_epoch_metrics=on_metrics,
    )

    # ---- stage 4: held-out test force MAE -----------------------------
    from cgnn_tpu.train.loop import run_epoch

    eval_jit = jax.jit(make_force_eval_step())
    _, test_m = run_epoch(
        eval_jit, state,
        batch_iterator(test_g, args.batch_size, nc, ec,
                       dense_m=cfg.max_num_nbr, snug=True, in_cap=0),
        train=False, log_fn=lambda *a, **k: None,
    )

    steady = sorted(epoch_s[1:])[len(epoch_s[1:]) // 2] if len(epoch_s) > 1 \
        else epoch_s[0]
    out = {
        "metric": "force_scale_proof",
        "n_frames": n_frames,
        "n_train": len(train_g),
        "trajectories": args.trajectories,
        "atoms_per_frame": sizes,
        "epochs": args.epochs,
        "batch_size": args.batch_size,
        "buckets": args.buckets,
        "scan_epochs": not args.no_scan,
        "layout": "dense",
        "featurize_s": round(featurize_s, 1),
        "epoch_s": epoch_s,
        "steady_epoch_s": steady,
        "end_to_end_frames_per_sec": round(len(train_g) / steady, 1),
        "curve": curve,
        "force_label_stats": force_label_stats,
        "best_val_force_mae": round(float(result["best"]), 5),
        "test_force_mae": round(float(test_m.get("force_mae", np.nan)), 5),
        "test_energy_mae": round(float(test_m.get("mae", np.nan)), 5),
        "compile_cache_warm": compile_cache_warm,
        "device": getattr(jax.devices()[0], "device_kind",
                          jax.devices()[0].platform),
    }
    line = json.dumps(jsonfinite(out))
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
