#!/usr/bin/env python
"""Quantized-program MAE drift gate (ISSUE 9): int8/bf16 vs f32.

The serving precision tiers (serve/quantize.py) are only shippable if
they are a precision DIAL, not an accuracy cliff: this harness trains
the standard model on the cached synthetic MP-like set (or restores
``--ckpt-dir``), builds the f32 / bf16 / int8 programs for the serving
shape ladder, runs the held-out split through ALL tiers in one process,
and gates the prediction-MAE ratio vs f32 at ``--tolerance`` (default
0.005 — the MAE_PARITY posture applied to serving precision).

Prints one JSON line; exit 1 if any tier exceeds the gate. Commit as
QUANT_PARITY.json next to the other parity artifacts.

Usage: python scripts/quant_parity.py [--n 4096] [--epochs 6]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--n", type=int, default=4096)
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--rungs", type=int, default=3)
    p.add_argument("--tolerance", type=float, default=0.005,
                   help="max allowed (tier_mae / f32_mae - 1)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", type=str, default="QUANT_PARITY.json")
    args = p.parse_args(argv)

    import jax
    import numpy as np

    from cgnn_tpu.data.dataset import (
        FeaturizeConfig,
        load_synthetic_mp,
        train_val_test_split,
    )
    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.serve.quantize import TIERS, build_tier_specs
    from cgnn_tpu.serve.shapes import plan_shape_set
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import fit
    from cgnn_tpu.train.step import make_predict_step

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = load_synthetic_mp(args.n, cfg, seed=11)
    train_g, val_g, test_g = train_val_test_split(graphs, 0.8, 0.1,
                                                 seed=args.seed)
    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                dense_m=12)
    nc, ec = capacities_for(train_g, args.batch_size, dense_m=12)
    example = next(batch_iterator(train_g, args.batch_size, nc, ec,
                                  dense_m=12))
    state = create_train_state(
        model, example, make_optimizer(optim="adam", lr=0.01),
        Normalizer.fit(np.stack([g.target for g in train_g])),
        rng=jax.random.key(args.seed),
    )
    state, _ = fit(
        state, train_g, val_g, epochs=args.epochs,
        batch_size=args.batch_size, seed=args.seed, print_freq=0,
        dense_m=12, log_fn=lambda *a, **k: None,
    )

    # every rung of the serving ladder, every tier, one process
    ladder = plan_shape_set(graphs, args.batch_size, rungs=args.rungs,
                            dense_m=12)
    specs = build_tier_specs(model, TIERS)
    pstep = jax.jit(make_predict_step())
    maes: dict[str, float] = {}
    per_rung: dict[str, list] = {}
    for tier in TIERS:
        st = specs[tier].state_for(state)
        abs_sum = count = 0.0
        rung_maes = []
        for shape in ladder:
            r_abs = r_cnt = 0.0
            group: list = []
            g_nodes = g_edges = 0

            def flush(group):
                nonlocal r_abs, r_cnt
                batch = ladder.pack(group, shape=shape)
                out = np.array(jax.device_get(pstep(st, batch)))
                tgt = np.stack([np.atleast_1d(g.target) for g in group])
                r_abs += float(np.abs(out[: len(group)] - tgt).sum())
                r_cnt += tgt.size

            for g in test_g:
                n, e = ladder.graph_counts(g)
                if group and not shape.fits(len(group) + 1, g_nodes + n,
                                            g_edges + e):
                    flush(group)
                    group, g_nodes, g_edges = [], 0, 0
                group.append(g)
                g_nodes += n
                g_edges += e
            if group:
                flush(group)
            rung_maes.append(round(r_abs / max(r_cnt, 1), 6))
            abs_sum += r_abs
            count += r_cnt
        maes[tier] = abs_sum / max(count, 1)
        per_rung[tier] = rung_maes

    ratios = {t: maes[t] / maes["f32"] for t in TIERS if t != "f32"}
    worst = max(ratios.values())
    out = {
        "metric": "quantized_program_mae_parity",
        "n_structures": args.n,
        "test_structures": len(test_g),
        "epochs": args.epochs,
        "rungs": args.rungs,
        "mae": {t: round(v, 6) for t, v in maes.items()},
        "mae_per_rung": per_rung,
        "ratio_vs_f32": {t: round(r, 5) for t, r in ratios.items()},
        "tolerance": args.tolerance,
        "pass": bool(worst <= 1.0 + args.tolerance),
        "device": str(jax.devices()[0].device_kind),
    }
    print(json.dumps(jsonfinite(out)))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(jsonfinite(out), fh, indent=1)
    return 0 if worst <= 1.0 + args.tolerance else 1


if __name__ == "__main__":
    sys.exit(main())
