#!/usr/bin/env python
"""Sweep: Pallas interval-one-hot kernel vs XLA sorted-scatter segment sum.

Default mode measures per-op forward / forward+backward times over
realistic (N, E, F, dtype, skew) shapes for each _TE chunk size.
CAVEAT (measured 2026-07-29): per-op timings through the axon device
tunnel bottom out at a ~17 µs dispatch floor regardless of shape (a
2x_plus_1 elementwise sweep reports an impossible 24 TB/s at E=800k), so
the op-level table is NOISE in this environment. Use ``--full-step``,
which times the real jitted train step on the bench workloads — that mode
produced the retirement data recorded in ops/pallas_scatter.py:
XLA wins MP b512 by ~3% and OC20 b128 by ~13%.

Run on the real chip: python scripts/sweep_pallas.py [--full-step]
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

import cgnn_tpu.ops.pallas_scatter as ps
from cgnn_tpu.ops.segment import aggregate_edge_messages


def make_case(n_nodes, deg_mean, f, dtype, skew, seed=0):
    """Sorted-centers COO case. skew='uniform'|'power' (degree distribution)."""
    rng = np.random.default_rng(seed)
    if skew == "uniform":
        deg = np.full(n_nodes, deg_mean, np.int64)
    else:  # power-law-ish: most nodes small, few huge
        deg = rng.pareto(1.5, n_nodes) + 1
        deg = np.minimum(deg / deg.mean() * deg_mean, deg_mean * 40).astype(np.int64)
    centers = np.repeat(np.arange(n_nodes, dtype=np.int32), deg)
    e = len(centers)
    msg = rng.standard_normal((e, f)).astype(np.float32)
    return (
        jnp.asarray(msg, dtype=dtype),
        jnp.asarray(centers),
        int(n_nodes),
        e,
    )


def time_fn(fn, *args, iters=30):
    out = fn(*args)
    jax.block_until_ready(out)
    best = np.inf
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e6  # us


def full_step_comparison():
    """Reliable mode: whole jitted train step per aggregation backend."""
    from bench import _bench_workload
    from cgnn_tpu.data.dataset import (
        FeaturizeConfig,
        load_synthetic_mp,
        load_synthetic_oc20,
    )
    from cgnn_tpu.ops.segment import set_default_aggregation_impl

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    workloads = [
        ("mp_b512", load_synthetic_mp(2048, cfg, seed=0), 512, 3, 24),
        ("oc20_b128", load_synthetic_oc20(384, cfg, seed=0), 128, 2, 16),
    ]
    for name, graphs, bs, buckets, n_timed in workloads:
        for impl in ("xla", "pallas"):
            set_default_aggregation_impl(impl)
            jax.clear_caches()
            r = _bench_workload(graphs, bs, buckets=buckets, n_timed=n_timed)
            print(
                f"{name:10s} {impl:7s} {r['structs_per_sec']:>11.0f} structs/s"
                f"  mfu {r['mfu']:.3f}"
            )
    set_default_aggregation_impl("xla")


def main():
    import sys

    if "--full-step" in sys.argv:
        full_step_comparison()
        return
    cases = [
        # label, nodes, mean degree, F
        ("mp_b512", 15872, 12, 64),
        ("oc20_b128", 17920, 12, 64),
        ("tiny_b512", 3584, 11, 64),
        ("wideF", 15872, 12, 128),
        ("hugeF", 15872, 12, 256),
    ]
    print(f"device: {jax.devices()[0].device_kind}")
    header = (
        f"{'case':10s} {'dtype':8s} {'skew':8s} {'E':>8s} "
        f"{'xla_fwd':>9s} {'pal_fwd':>9s} {'xla_fb':>9s} {'pal_fb':>9s}  best"
    )
    for te in (256, 512, 1024):
        ps._TE = te
        jax.clear_caches()
        print(f"\n=== _TE={te} ===\n{header}")
        for label, n, deg, f in cases:
            for dtype in (jnp.float32, jnp.bfloat16):
                for skew in ("uniform", "power"):
                    msg, centers, nn, e = make_case(n, deg, f, dtype, skew)

                    def fwd(impl):
                        return jax.jit(
                            lambda m, c: aggregate_edge_messages(m, c, nn, impl=impl)
                        )

                    def fwdbwd(impl):
                        def loss(m, c):
                            return jnp.sum(
                                aggregate_edge_messages(m, c, nn, impl=impl) ** 2
                            )
                        return jax.jit(jax.grad(loss, argnums=0))

                    tx = time_fn(fwd("xla"), msg, centers)
                    tp = time_fn(fwd("pallas"), msg, centers)
                    txb = time_fn(fwdbwd("xla"), msg, centers)
                    tpb = time_fn(fwdbwd("pallas"), msg, centers)
                    best = "pallas" if tpb < txb else "xla"
                    print(
                        f"{label:10s} {np.dtype(dtype).name:8s} {skew:8s} {e:8d} "
                        f"{tx:9.1f} {tp:9.1f} {txb:9.1f} {tpb:9.1f}  {best}"
                    )


if __name__ == "__main__":
    main()
