#!/usr/bin/env python
"""Scan-epochs vs per-step convergence at multi-bucket (VERDICT r2 #5).

Trains the same multi-bucket MP-like workload twice — per-step
device-resident loop vs whole-epoch scan dispatch — with identical seeds
and compares the val-MAE trajectory. The r2 scan driver's deterministic
round-robin chunking converged measurably slower than the per-step loop's
weighted-random interleave; the randomized chunk scheduling
(ScanEpochDriver, r3) is accepted if the curves match within seed noise
(third run: per-step at a different seed = the noise yardstick).

Prints one JSON line: {"per_step": [...], "scan": [...],
"per_step_seed2": [...], "final_gap_vs_noise": ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def train_once(graphs, *, epochs, batch_size, buckets, seed, scan):
    import jax
    import numpy as np

    from cgnn_tpu.data.dataset import train_val_test_split
    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import fit

    train_g, val_g, _ = train_val_test_split(graphs, 0.8, 0.1, seed=0)
    model = CrystalGraphConvNet(
        atom_fea_len=64, n_conv=3, h_fea_len=128,
        dtype=jax.numpy.bfloat16, dense_m=12,
    )
    tx = make_optimizer(optim="sgd", lr=0.02, lr_milestones=[10**9])
    normalizer = Normalizer.fit(np.stack([g.target for g in train_g]))
    nc, ec = capacities_for(train_g, batch_size, dense_m=12, snug=True)
    example = next(batch_iterator(train_g, batch_size, nc, ec, dense_m=12,
                                  snug=True))
    state = create_train_state(model, example, tx, normalizer,
                               rng=jax.random.key(seed))
    curve = []
    _, result = fit(
        state, train_g, val_g, epochs=epochs, batch_size=batch_size,
        buckets=buckets, seed=seed, print_freq=0, dense_m=12, snug=True,
        device_resident=True, scan_epochs=scan,
        log_fn=lambda *a, **k: None,
        on_epoch_metrics=lambda e, tm, vm: curve.append(
            round(float(vm["mae"]), 5)),
    )
    return curve


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--n", type=int, default=24576)
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--buckets", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = load_synthetic_mp(args.n, cfg, seed=3)

    kw = dict(epochs=args.epochs, batch_size=args.batch_size,
              buckets=args.buckets)
    per_step = train_once(graphs, seed=args.seed, scan=False, **kw)
    scan = train_once(graphs, seed=args.seed, scan=True, **kw)
    per_step2 = train_once(graphs, seed=args.seed + 1, scan=False, **kw)

    noise = abs(per_step[-1] - per_step2[-1])
    gap = abs(scan[-1] - per_step[-1])
    print(json.dumps(jsonfinite({
        "metric": "scan_vs_per_step_val_mae",
        "per_step": per_step,
        "scan": scan,
        "per_step_seed2": per_step2,
        "final_gap": round(gap, 5),
        "seed_noise": round(noise, 5),
        "within_noise": bool(gap <= max(noise, 0.002) * 1.5),
    })))
    return 0


if __name__ == "__main__":
    sys.exit(main())
