#!/usr/bin/env python
"""Bench-round regression diff: the latest ``BENCH_r*.json`` vs the
previous one, failing loudly on >20% regression of any named key.

The BENCH trajectory (BENCH_r01..r05) is the repo's performance memory,
but nothing READ it — a silent 20% throughput slide would ship (PERF.md
§8 only caught the r3->r4 drift because a human went looking). This
script is the automated reader:

- flattens each round's ``parsed`` payload (nested sections join with
  '.'), selects the named higher-is-better keys (default: every
  throughput figure plus MFU and padding efficiency),
- prints the full old/new/delta table,
- emits a GitHub annotation line (``::error``/``::notice``) per
  regressed key, and exits 1 when any named key regressed beyond the
  threshold.

``--ledger BASELINE NEW`` additionally diffs two ``AUDIT_LEDGER.json``
payloads (ISSUE 8) through the same budget semantics with the sign
flipped: the gated keys (bytes, peak temp memory, bytes/FLOP) are
LOWER-is-better, and a program or key that disappears from the new
ledger is a regression — a budget that stopped being measured is how a
regression hides. The diff logic lives in
``cgnn_tpu.analysis.program_audit.diff_ledgers`` (stdlib-only), shared
with ``graftaudit.py --ci``.

CI wires it as a NON-BLOCKING annotation step (continue-on-error: the
bench numbers come from whatever machine ran the round, so a regression
is a flag for the next bench run on real hardware, not a merge gate).

Usage::

    python scripts/bench_regress.py                 # repo-root BENCH_r*
    python scripts/bench_regress.py --dir /path --threshold 0.2
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# higher-is-better keys checked against the threshold; everything else
# in the flattened payload is printed for context only
DEFAULT_KEYS = (
    "value",
    "atoms_per_sec",
    "mfu",
    "epoch_driver_structs_per_sec",
    "inference_structs_per_sec",
    "inference_e2e_structs_per_sec",
    "inference_e2e_multidev_structs_per_sec",
    # ISSUE 11: raw-wire ingest — the e2e rate through the in-program
    # neighbor search and the structural bytes-on-wire win (both
    # higher-is-better; dropping either from a bench round is how the
    # raw path would silently rot)
    "inference_e2e_raw_structs_per_sec",
    "ingest_wire_bytes_ratio",
    "ingest_raw_admit_share",
    "padding_eff_nodes",
    "padding_eff_edges",
    # ISSUE 19: priority serving — aggregate goodput under a mixed-class
    # load and the share of would-be padding that backfill converted to
    # answers (both higher-is-better; a bench round that stops measuring
    # them is how the front-door scheduler would silently rot)
    "serve_goodput_structs_per_sec",
    "serve_padding_fill_share",
    # ISSUE 20: one fleet cache — the partitioned fleet's effective hit
    # ratio on the Zipf keyset and its gain over the replicated
    # baseline (both higher-is-better; a bench round that stops
    # measuring them is how cache partitioning would silently rot).
    # The host-dependent fingerprint_blake2b_speedup is deliberately
    # NOT gated: it flips below 1 on SHA-NI hosts by design.
    "median_effective_hit_ratio.cachepart",
    "effective_hit_ratio_gain",
    "oc20.oc20_structs_per_sec",
    "tiny.tiny_structs_per_sec",
    "coo_layout.coo_structs_per_sec",
    "force_task.force_coo_structs_per_sec",
    "force_task.force_dense_structs_per_sec",
)

_ROUND = re.compile(r"BENCH_r(\d+)\.json$")


def find_rounds(bench_dir: str) -> list[tuple[int, str]]:
    """[(round number, path)] sorted ascending."""
    out = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")):
        m = _ROUND.search(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    return sorted(out)


def flatten(payload: dict, prefix: str = "") -> dict:
    """Nested dicts -> {'a.b': v} for every numeric leaf."""
    out = {}
    for k, v in payload.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(flatten(v, prefix=f"{key}."))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = float(v)
    return out


def load_parsed(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    return flatten(doc.get("parsed", doc))


def diff_rounds(old: dict, new: dict, keys, threshold: float) -> dict:
    """-> {"rows": [...], "regressions": [...]} (rows cover every named
    key present in either round; a key missing from the NEW round is a
    regression too — a bench that stopped measuring something is how a
    regression hides)."""
    rows, regressions = [], []
    for key in keys:
        o, n = old.get(key), new.get(key)
        if o is None and n is None:
            continue
        row = {"key": key, "old": o, "new": n}
        if o is None:
            row["note"] = "new key"
        elif n is None:
            row["note"] = "DROPPED from latest round"
            regressions.append(row)
        elif o > 0:
            ratio = n / o
            row["ratio"] = round(ratio, 4)
            if ratio < 1.0 - threshold:
                row["note"] = (
                    f"REGRESSION: {100 * (1 - ratio):.1f}% below previous"
                )
                regressions.append(row)
        rows.append(row)
    return {"rows": rows, "regressions": regressions}


def diff_ledger_files(baseline_path: str, new_path: str,
                      threshold: float, github: bool) -> int:
    """AUDIT_LEDGER budget diff (lower-is-better, dropped key =
    regression) -> number of hard regressions. Shares
    program_audit.diff_ledgers with graftaudit --ci."""
    from cgnn_tpu.analysis.program_audit import diff_ledgers, load_ledger

    diff = diff_ledgers(load_ledger(baseline_path), load_ledger(new_path),
                        threshold=threshold)
    print(f"bench_regress: audit ledger {os.path.basename(baseline_path)} "
          f"-> {os.path.basename(new_path)} (threshold {threshold:.0%}, "
          f"lower-is-better)")
    for row in diff["rows"]:
        o = "-" if row["old"] is None else f"{row['old']}"
        n = "-" if row["new"] is None else f"{row['new']}"
        ratio = f"{row['ratio']:.3f}x" if "ratio" in row else ""
        print(f"  {row['key']:<45} {o:>14} -> {n:>14}  {ratio:>8}  "
              f"{row.get('note', '')}")
    for row in diff["regressions"]:
        msg = (f"audit budget {row['key']}: {row.get('note', '')} "
               f"(baseline {row['old']}, new {row['new']})")
        if github:
            print(f"::error title=audit budget::{msg}")
        print(f"bench_regress: {msg}", file=sys.stderr)
    for row in diff["warnings"]:
        msg = (f"audit budget {row['key']} drifted under a different jax "
               f"than the baseline's: {row.get('note', '')}")
        if github:
            print(f"::warning title=audit budget skew::{msg}")
        print(f"bench_regress: {msg}")
    if not diff["regressions"]:
        print(f"bench_regress: audit budgets ok ({len(diff['rows'])} keys"
              f"{', version skew' if diff['version_skew'] else ''})")
    return len(diff["regressions"])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    p.add_argument("--threshold", type=float, default=0.2,
                   help="fractional drop that counts as a regression")
    p.add_argument("--keys", default="",
                   help="comma-separated override of the named keys")
    p.add_argument("--github", action="store_true",
                   help="emit GitHub workflow annotation lines")
    p.add_argument("--ledger", nargs=2, metavar=("BASELINE", "NEW"),
                   help="also budget-diff two AUDIT_LEDGER.json files "
                        "(lower-is-better keys; dropped key = regression)")
    args = p.parse_args(argv)

    ledger_regressions = 0
    if args.ledger:
        ledger_regressions = diff_ledger_files(
            args.ledger[0], args.ledger[1], args.threshold, args.github)

    rounds = find_rounds(args.dir)
    if not rounds:
        print(f"bench_regress: no BENCH_r*.json under {args.dir} — "
              f"nothing to do")
        return 1 if ledger_regressions else 0
    if len(rounds) == 1:
        # exactly one round is NOT a silent pass: it is the baseline
        # every later round will be judged against — say so explicitly
        # (an empty-looking step that "succeeded" is how a broken glob
        # or a wiped artifact dir hides)
        n, path = rounds[0]
        named = len([k for k in DEFAULT_KEYS
                     if k in load_parsed(path)])
        msg = (f"single bench round r{n:02d} "
               f"({os.path.basename(path)}, {named} named keys present) "
               f"— baseline recorded, nothing to diff yet")
        if args.github:
            print(f"::notice title=bench baseline recorded::{msg}")
        print(f"bench_regress: {msg}")
        return 1 if ledger_regressions else 0
    (old_n, old_path), (new_n, new_path) = rounds[-2], rounds[-1]
    keys = ([k.strip() for k in args.keys.split(",") if k.strip()]
            or list(DEFAULT_KEYS))
    result = diff_rounds(load_parsed(old_path), load_parsed(new_path),
                         keys, args.threshold)

    print(f"bench_regress: r{old_n:02d} -> r{new_n:02d} "
          f"(threshold {args.threshold:.0%})")
    for row in result["rows"]:
        o = "-" if row["old"] is None else f"{row['old']:.4g}"
        n = "-" if row["new"] is None else f"{row['new']:.4g}"
        ratio = f"{row['ratio']:.3f}x" if "ratio" in row else ""
        note = row.get("note", "")
        print(f"  {row['key']:<45} {o:>12} -> {n:>12}  {ratio:>8}  {note}")

    if result["regressions"]:
        for row in result["regressions"]:
            msg = (f"BENCH r{old_n:02d}->r{new_n:02d} {row['key']}: "
                   f"{row.get('note', '')} "
                   f"(old {row['old']}, new {row['new']})")
            if args.github:
                print(f"::error title=bench regression::{msg}")
            print(f"bench_regress: {msg}", file=sys.stderr)
        return 1
    msg = (f"no >{args.threshold:.0%} regressions across "
           f"{len(result['rows'])} named keys (r{old_n:02d}->r{new_n:02d})")
    if args.github:
        print(f"::notice title=bench regression check::{msg}")
    print(f"bench_regress: {msg}")
    return 1 if ledger_regressions else 0


if __name__ == "__main__":
    sys.exit(main())
