#!/usr/bin/env bash
# Crash-recovery smoke (ISSUE 2 acceptance; .github/workflows/tier1.yml):
#
#  1. SIGTERM a training run mid-flight -> it must save a resumable
#     checkpoint at the next boundary and exit with the distinct
#     resumable code 75;
#  2. kill -9 a second run (no grace at all) -> the versioned atomic
#     checkpoint layout must still hold a committed save;
#  3. resume both with --resume auto -> the runs complete to the full
#     epoch count, proving the checkpoint -> resume -> finish loop.
#
# Uses the COO layout + synthetic data so it runs anywhere jax[cpu] does.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
# enough epochs that the kill always lands while training is still
# running (epochs are sub-second once compiled; the commit poll below
# fires within 0.2 s of the first save)
EPOCHS=40
CKPT=$(mktemp -d)
trap 'rm -rf "$CKPT"' EXIT
ARGS=(--synthetic 48 --device cpu --epochs "$EPOCHS" --optim Adam -b 16
      --radius 5 --layout coo --print-freq 0)

wait_for_commit() { # <dir>: block until a committed save exists
  for _ in $(seq 1 900); do
    compgen -G "$1/ckpt-*/MANIFEST.json" >/dev/null && return 0
    sleep 0.2
  done
  echo "no committed checkpoint appeared under $1" >&2
  return 1
}

echo "== leg 1: SIGTERM -> resumable exit 75 =="
python train.py "${ARGS[@]}" --ckpt-dir "$CKPT/a" >"$CKPT/run_a.log" 2>&1 &
PID=$!
wait_for_commit "$CKPT/a"
kill -TERM "$PID"
set +e; wait "$PID"; RC=$?; set -e
if [ "$RC" -ne 75 ]; then
  echo "expected resumable exit 75, got $RC" >&2
  tail -30 "$CKPT/run_a.log" >&2
  exit 1
fi
grep -q "preempted: resumable checkpoint saved" "$CKPT/run_a.log"

echo "== leg 2: kill -9 mid-run leaves a committed save =="
python train.py "${ARGS[@]}" --ckpt-dir "$CKPT/b" >"$CKPT/run_b.log" 2>&1 &
PID=$!
wait_for_commit "$CKPT/b"
kill -KILL "$PID"
set +e; wait "$PID"; RC=$?; set -e
[ "$RC" -eq 137 ] || { echo "expected 137 after kill -9, got $RC" >&2; exit 1; }
compgen -G "$CKPT/b/ckpt-*/MANIFEST.json" >/dev/null

echo "== leg 3: --resume auto completes both runs to the full epoch count =="
for leg in a b; do
  python train.py "${ARGS[@]}" --ckpt-dir "$CKPT/$leg" --resume auto \
    >"$CKPT/resume_$leg.log" 2>&1
  grep -q "resumed from" "$CKPT/resume_$leg.log"
  grep -q "Epoch $((EPOCHS - 1)):" "$CKPT/resume_$leg.log"
  grep -Fq "** test mae:" "$CKPT/resume_$leg.log"
  echo "leg $leg resumed and completed"
done

echo "crash-recovery smoke: PASS"
