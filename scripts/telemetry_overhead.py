#!/usr/bin/env python
"""Measure --telemetry step overhead on the scan dispatch path.

The ISSUE-1 acceptance criterion: with step-level telemetry on the bench
PRIMARY workload, per-step records stream from inside the scan AND the
measured step-time overhead stays < 5%. This harness builds the PRIMARY
MP-like workload (bench.py distribution), drives ScanEpochDriver epochs
with telemetry off vs step INTERLEAVED in one process (the only
trustworthy comparison on the tunneled runtime — PERF.md §8), and prints
one JSON line:

    {"off_s": [...], "step_s": [...], "overhead": <median ratio - 1>,
     "step_records": N, "parity": true}

Run on the real chip for the acceptance number; on CPU it still verifies
streaming + parity and gives an upper-bound overhead reading.

Usage: python scripts/telemetry_overhead.py [--graphs 512] [--epochs 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def build(args, telemetry):
    import numpy as np

    import jax
    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import ScanEpochDriver
    from cgnn_tpu.train.step import make_eval_step, make_train_step

    graphs = load_synthetic_mp(
        args.graphs, FeaturizeConfig(radius=8.0, max_num_nbr=12), seed=0
    )
    dense_m = 12 if args.layout == "dense" else None
    node_cap, edge_cap = capacities_for(graphs, args.batch_size,
                                        dense_m=dense_m, snug=True)
    batches = list(batch_iterator(graphs, args.batch_size, node_cap,
                                  edge_cap, dense_m=dense_m, snug=True))
    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                dense_m=dense_m)
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10**9])
    state = create_train_state(
        model, batches[0], tx,
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(0),
    )
    drv = ScanEpochDriver(
        make_train_step(grad_health=telemetry.step_level),
        make_eval_step(),
        batches, batches[:1], np.random.default_rng(0),
        telemetry=telemetry,
    )
    return state, drv


def drive(args, telemetry):
    state, drv = build(args, telemetry)
    state = drv.warm(state)
    times = []
    final = None
    for e in range(args.epochs):
        t0 = time.perf_counter()
        state, tm, _ = drv.run_epoch_pair(state, first=e == 0)
        times.append(round(time.perf_counter() - t0, 4))
        final = tm
    import jax
    import numpy as np

    params = jax.tree_util.tree_map(np.asarray, state.params)
    return times, final, params


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--graphs", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--layout", choices=["dense", "coo"],
                   default=os.environ.get("CGNN_BENCH_LAYOUT", "dense"))
    p.add_argument("--out", type=str, default="")
    args = p.parse_args()

    from cgnn_tpu.observe import Telemetry

    import numpy as np

    off_s, step_s = [], []
    step_records = 0
    params_off = params_step = None
    log_dir = tempfile.mkdtemp(prefix="telem_overhead_")
    # interleave off/step rounds (PERF.md §8: in-process interleaved
    # comparisons only; order rotated per round)
    for r in range(args.rounds):
        order = ["off", "step"] if r % 2 == 0 else ["step", "off"]
        for mode in order:
            telemetry = (
                Telemetry.disabled() if mode == "off"
                else Telemetry("step", os.path.join(log_dir, f"r{r}"))
            )
            times, _, params = drive(args, telemetry)
            if mode == "off":
                off_s.append(sum(times))
                params_off = params
            else:
                step_s.append(sum(times))
                params_step = params
                if telemetry.stream is not None:
                    import jax

                    jax.effects_barrier()
                    step_records = max(
                        step_records,
                        len(telemetry.stream.records("train")),
                    )
                telemetry.close()

    import jax

    parity = all(
        np.array_equal(a, b) for a, b in zip(
            jax.tree_util.tree_leaves(params_off),
            jax.tree_util.tree_leaves(params_step),
        )
    )
    overhead = float(np.median(step_s) / np.median(off_s) - 1.0)
    out = {
        "off_s": off_s,
        "step_s": step_s,
        "overhead": round(overhead, 4),
        "step_records": step_records,
        "parity": parity,
        "device": str(jax.devices()[0].device_kind
                      or jax.devices()[0].platform),
        "layout": args.layout,
        "epochs_per_round": args.epochs,
    }
    line = json.dumps(jsonfinite(out))
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if parity else 1


if __name__ == "__main__":
    sys.exit(main())
