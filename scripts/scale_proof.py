#!/usr/bin/env python
"""MP-146k-scale end-to-end proof (BASELINE config #2 at real scale).

Real Materials Project data is unavailable offline, so this exercises the
full pipeline at MP-146k SCALE with the synthetic MP-like distribution
(lognormal ~30 atoms — the same distribution bench.py measures):

  1. generate + featurize N structures (timed: host preprocessing rate).
     Single-process by design ON THIS HOST: the box exposes one CPU core,
     so `featurize_directory_parallel`'s worker pool cannot speed this
     stage here (VERDICT r3 weak #8); the parallel path exists and is
     dirty-directory-tested for real multi-core preprocessing boxes
     (data/cache.py, tests/test_cif_corpus.py).
  2. save + mmap-reload the graph cache (timed; the offline-preprocess
     artifact SURVEY.md §7 phase 4 prescribes)
  3. train --epochs epochs of band-gap-style regression on the visible
     device (timed per epoch: END-TO-END throughput including host packing
     and prefetch, not just the jitted step bench.py isolates), with
     --pack-once exercising the cached-dataset fast path

Prints one JSON line with every stage's numbers.

Usage: python scripts/scale_proof.py [--n 146210] [--epochs 3] [--pack-once]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--n", type=int, default=146_210)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--buckets", type=int, default=3)
    p.add_argument("--pack-once", action="store_true")
    p.add_argument("--device-resident", action="store_true",
                   help="stage packed batches into HBM once (implies "
                        "--pack-once)")
    p.add_argument("--scan-epochs", action="store_true",
                   help="one lax.scan dispatch per bucket shape per epoch "
                        "(implies --device-resident)")
    p.add_argument("--cache", type=str, default="/tmp/mp146k_cache.npz")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--device", choices=["auto", "cpu"], default="auto")
    p.add_argument("--layout", choices=["dense", "coo"], default="dense")
    p.add_argument("--compile-cache", type=str, default="/tmp/jax_cache",
                   metavar="DIR",
                   help="persistent XLA compile cache ('' disables); "
                        "warmth is recorded in the output JSON")
    p.add_argument("--compact", choices=["auto", "on", "off"],
                   default="auto",
                   help="stage raw atoms+distances and featurize on device "
                        "(data/compact.py); auto = on when scan+dense "
                        "supports it")
    args = p.parse_args(argv)
    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    compile_cache_warm = False
    if args.compile_cache:
        try:
            # persistent compile cache: scan-program compiles (tens of
            # seconds each through a high-latency link) become disk hits
            # on re-runs; warmth is recorded in the output JSON so cold
            # and warm first-epoch numbers are never silently mixed
            compile_cache_warm = bool(os.path.isdir(args.compile_cache)
                                      and os.listdir(args.compile_cache))
            jax.config.update("jax_compilation_cache_dir",
                              args.compile_cache)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
        except Exception as e:  # noqa: BLE001 — cache is best-effort
            print(f"compilation cache unavailable: {e}", file=sys.stderr)
    import numpy as np

    from cgnn_tpu.data.cache import load_graph_cache, save_graph_cache
    from cgnn_tpu.data.dataset import (
        FeaturizeConfig,
        load_synthetic_mp,
        train_val_test_split,
    )
    from cgnn_tpu.data.graph import pack_graphs
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import capacities_for, fit

    out: dict = {"metric": "mp146k_scale_proof", "n_structures": args.n,
                 "compile_cache_warm": compile_cache_warm}

    # 1. featurize (generation + neighbor search + Gaussian expansion)
    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    if os.path.exists(args.cache):
        t0 = time.perf_counter()
        graphs = load_graph_cache(args.cache)[: args.n]
        out["cache_load_s"] = round(time.perf_counter() - t0, 1)
        if len(graphs) < args.n:
            print(f"cache {args.cache} holds only {len(graphs)} graphs "
                  f"(< --n {args.n}); delete it to regenerate",
                  file=sys.stderr)
            return 1
        # report what was actually used, not what was requested
        out["n_structures"] = len(graphs)
        print(f"loaded {len(graphs)} graphs from cache "
              f"({out['cache_load_s']}s)", file=sys.stderr)
    else:
        t0 = time.perf_counter()
        graphs = load_synthetic_mp(args.n, cfg, seed=args.seed)
        dt = time.perf_counter() - t0
        out["featurize_s"] = round(dt, 1)
        out["featurize_structs_per_sec"] = round(args.n / dt, 1)
        # 2. cache round trip
        t0 = time.perf_counter()
        save_graph_cache(graphs, args.cache)
        out["cache_save_s"] = round(time.perf_counter() - t0, 1)
        out["cache_mb"] = round(os.path.getsize(args.cache) / 1e6, 1)
        t0 = time.perf_counter()
        graphs = load_graph_cache(args.cache)
        out["cache_load_s"] = round(time.perf_counter() - t0, 1)

    # 3. end-to-end training
    train_g, val_g, _test_g = train_val_test_split(graphs, 0.9, 0.05,
                                                   seed=args.seed)
    out["n_train"] = len(train_g)
    layout_m = cfg.max_num_nbr if args.layout == "dense" else None
    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                dtype=jax.numpy.bfloat16, dense_m=layout_m)
    tx = make_optimizer(optim="adam", lr=0.01, lr_milestones=[10**9])
    normalizer = Normalizer.fit(np.stack([g.target for g in train_g]))
    node_cap, edge_cap = capacities_for(train_g, args.batch_size,
                                        dense_m=layout_m, snug=True)
    example = pack_graphs(
        sorted(train_g[: args.batch_size // 2], key=lambda g: g.num_nodes),
        node_cap, edge_cap, args.batch_size, dense_m=layout_m,
    )
    state = create_train_state(model, example, tx, normalizer,
                               rng=jax.random.key(args.seed))

    compact_spec = None
    if args.compact == "on" and not (args.scan_epochs and layout_m):
        print("--compact on requires --scan-epochs and --layout dense",
              file=sys.stderr)
        return 2
    if args.compact != "off" and args.scan_epochs and layout_m is not None:
        from cgnn_tpu.data.compact import CompactSpec, CompactUnsupported

        try:
            t0 = time.perf_counter()
            compact_spec = CompactSpec.build(
                train_g + val_g, cfg.gdf(), dense_m=layout_m,
                edge_dtype=jax.numpy.bfloat16,
            )
            out["compact_spec_build_s"] = round(time.perf_counter() - t0, 1)
        except CompactUnsupported as e:
            if args.compact == "on":
                raise
            print(f"compact staging unavailable ({e}); using full "
                  f"staging", file=sys.stderr)
    out["compact"] = compact_spec is not None

    epoch_times: list[float] = []
    last_t = [time.perf_counter()]

    def on_epoch_metrics(_epoch, _train_m, _val_m):
        now = time.perf_counter()
        epoch_times.append(now - last_t[0])
        last_t[0] = now

    state, result = fit(
        state, train_g, val_g, epochs=args.epochs,
        batch_size=args.batch_size, node_cap=node_cap, edge_cap=edge_cap,
        buckets=args.buckets, seed=args.seed, print_freq=0,
        pack_once=args.pack_once, device_resident=args.device_resident,
        scan_epochs=args.scan_epochs, snug=True,
        dense_m=layout_m, on_epoch_metrics=on_epoch_metrics,
        compact=compact_spec,
        log_fn=lambda msg: print(msg, file=sys.stderr),
    )
    if "staging" in result:
        # first-epoch accounting (VERDICT r4 missing #1): how the one-time
        # cost before steady epochs splits into host packing, stack+stage
        # dispatch, and the remainder (H2D completion + compiles + first
        # dispatches, inseparable through an async link)
        st = dict(result["staging"])
        if epoch_times:
            st["compile_stage_first_dispatch_s"] = round(
                epoch_times[0] - st["pack_s"]
                - st["stack_stage_dispatch_s"], 1
            )
        out["first_epoch_breakdown"] = st
    # steady state: exclude the first epoch (compiles + pack_once packing)
    # and use the MEDIAN — the scan driver's randomly drawn chunk lengths
    # can first-compile in a later epoch too (observed: an 8.1 s epoch 2
    # inside a 2.9 s steady run), and a mean would book that compile as
    # steady-state cost
    steady = epoch_times[1:] or epoch_times
    out["epoch_s"] = [round(t, 1) for t in epoch_times]
    out["steady_epoch_s"] = round(float(np.median(steady)), 1)
    out["end_to_end_structs_per_sec"] = round(
        len(train_g) / float(np.median(steady)), 1)
    out["pack_once"] = bool(
        args.pack_once or args.device_resident or args.scan_epochs
    )
    out["device_resident"] = bool(args.device_resident or args.scan_epochs)
    out["scan_epochs"] = bool(args.scan_epochs)
    out["layout"] = args.layout
    out["final_val_mae"] = round(float(result["best"]), 5)
    out["device"] = str(jax.devices()[0].device_kind)
    print(json.dumps(jsonfinite(out)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
