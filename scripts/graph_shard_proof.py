#!/usr/bin/env python
"""Graph-sharding composition proof: the dense fast path sharded by node
strips vs the COO fallback it replaces (VERDICT r4 #3).

Four measurements on ONE device set (default: the 8 virtual CPU devices —
the only multi-device fabric this machine can form; one real TPU chip is
visible, so true multi-chip ICI rates are unmeasurable here):

  dp8_dense      — plain data parallelism x8, dense layout
  dp8_coo        — plain data parallelism x8, flat COO layout
  dp4xgp2_dense  — ('data' 4, 'graph' 2): the NEW composition — dense
                   layout, node-strip shards, per-shard scatter-free
                   transposes
  dp4xgp2_coo    — the OLD --graph-shards path: flat COO + edge sharding
                   (what every sharded run was forced onto before)

The 2-D configs run 2x the per-data-shard batch so every config moves the
same global structures per step across the same 8 devices.

CONFOUND, and how the ratios de-confound it: the dense layout's 2.2x win
over COO (BENCH r4) is a TPU phenomenon — XLA's TPU scatter runs ~50x
below HBM bandwidth, while CPU scatters are fine and the dense layout's
padded [N, M] work makes dense SLOWER than COO on CPU. Absolute CPU
rates therefore say nothing about TPU. What transfers is the RELATIVE
structure:

  layout_ratio_sharded ~= layout_ratio_unsharded
      -> sharding preserves each layout's relative cost, so the
         TPU-measured dense advantage carries over to sharded TPU runs
  sharding_overhead_dense = dp4xgp2_dense / dp8_dense
      -> what the graph axis itself costs the dense path (collectives +
         replicated BN2/head + the tier-M transpose backward)

Timing follows bench.py's fencing convention: each round ends in a VALUE
FETCH of the last step's metrics through the donated-state chain.

Prints one JSON line; --out writes it to a file (GRAPH_SHARD_PROOF.json).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def _timed_rounds(step, state, device_batches, structs_per_batch, n_timed):
    import numpy as np

    best = 0.0
    rounds_s = []
    for _ in range(3):
        structures = 0.0
        t0 = time.perf_counter()
        metrics = None
        for i in range(n_timed):
            k = i % len(device_batches)
            state, metrics = step(state, device_batches[k])
            structures += structs_per_batch[k]
        float(np.asarray(metrics["loss_sum"]))
        dt = time.perf_counter() - t0
        rounds_s.append(round(dt, 4))
        best = max(best, structures / dt)
    return state, best, rounds_s


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--n", type=int, default=768)
    p.add_argument("--batch-size", type=int, default=16,
                   help="per data-shard batch size")
    p.add_argument("--n-timed", type=int, default=12)
    p.add_argument("--platform", choices=["cpu", "auto"], default="cpu")
    p.add_argument("--out", default="")
    args = p.parse_args(argv)

    if args.platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
    from cgnn_tpu.data.graph import capacities_for
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.parallel.data_parallel import (
        make_parallel_train_step,
        parallel_batches,
        replicate_state,
        shard_leading_axis,
    )
    from cgnn_tpu.parallel.edge_parallel import (
        make_dp_edge_parallel_train_step,
        shard_stacked_batch,
    )
    from cgnn_tpu.parallel.mesh import make_2d_mesh, make_mesh
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer

    if len(jax.devices()) < 8:
        print("needs 8 devices", file=sys.stderr)
        return 1

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = load_synthetic_mp(args.n, cfg, seed=0)
    targets = np.stack([g.target for g in graphs])
    f, h, n_conv = 64, 128, 3
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10_000])
    edge_dtype = jax.numpy.bfloat16

    def fresh_state(model, example):
        return create_train_state(model, example, tx, Normalizer.fit(targets))

    def stacked_batches(n_data, batch_size, **kw):
        bs = list(parallel_batches(
            graphs, n_data, batch_size, kw.pop("node_cap"),
            kw.pop("edge_cap"), shuffle=True,
            rng=np.random.default_rng(0), edge_dtype=edge_dtype, **kw,
        ))
        per = [float(np.asarray(b.graph_mask).sum()) for b in bs]
        return bs, per

    result: dict = {
        "metric": "graph_shard_composition",
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
        "n_structures": args.n,
        "batch_size_per_data_shard": args.batch_size,
        "note": (
            "8 virtual CPU devices (single real TPU chip: multi-chip ICI "
            "unmeasurable on this machine); per-chip ratios between configs "
            "on the same virtual fabric are the signal, absolute rates are "
            "not TPU rates"
        ),
    }

    mesh8 = make_mesh(8)
    mesh2d = make_2d_mesh(2, data_shards=4)
    b1, b2 = args.batch_size, 2 * args.batch_size

    model_dense = CrystalGraphConvNet(
        atom_fea_len=f, n_conv=n_conv, h_fea_len=h,
        dtype=jax.numpy.bfloat16, dense_m=12)
    model_dense_gp = CrystalGraphConvNet(
        atom_fea_len=f, n_conv=n_conv, h_fea_len=h,
        dtype=jax.numpy.bfloat16, dense_m=12, edge_axis_name="graph")
    model_coo = CrystalGraphConvNet(atom_fea_len=f, n_conv=n_conv,
                                    h_fea_len=h, dtype=jax.numpy.bfloat16)
    model_coo_gp = CrystalGraphConvNet(
        atom_fea_len=f, n_conv=n_conv, h_fea_len=h,
        dtype=jax.numpy.bfloat16, edge_axis_name="graph")

    def run(key, bs, per, mesh, model, apply_model, step):
        import dataclasses

        # init with the plain model on a transpose-free example (params do
        # not depend on the mapping fields, and per-shard stacked mappings
        # only trace inside shard_map)
        example = dataclasses.replace(
            jax.tree_util.tree_map(lambda x: x[0], bs[0]),
            in_slots=None, in_mask=None, over_slots=None, over_nodes=None,
            over_mask=None)
        state = replicate_state(
            fresh_state(model, example).replace(apply_fn=apply_model.apply),
            mesh)
        put = (shard_stacked_batch if "graph" in mesh.axis_names
               else shard_leading_axis)
        dev = [put(b, mesh) for b in bs]
        state, _ = step(state, dev[0])  # compile
        _, rate, rounds = _timed_rounds(step, state, dev, per, args.n_timed)
        result[key] = {"structs_per_sec_per_chip": round(rate / 8, 1),
                       "rounds_s": rounds}

    # dense capacities: shared between dp8 (batch b1) and 2-D (batch b2 =
    # same global structures/step)
    nc1, ec1 = capacities_for(graphs, b1, dense_m=12, snug=True,
                              node_multiple=16)
    nc2, ec2 = capacities_for(graphs, b2, dense_m=12, snug=True,
                              node_multiple=16)
    bs, per = stacked_batches(8, b1, node_cap=nc1, edge_cap=ec1, dense_m=12,
                              snug=True)
    run("dp8_dense", bs, per, mesh8, model_dense, model_dense,
        make_parallel_train_step(mesh8))

    bs, per = stacked_batches(4, b2, node_cap=nc2, edge_cap=ec2, dense_m=12,
                              snug=True, transpose_shards=2)
    run("dp4xgp2_dense", bs, per, mesh2d, model_dense, model_dense_gp,
        make_dp_edge_parallel_train_step(mesh2d, dense=True))

    nc1c, ec1c = capacities_for(graphs, b1, snug=True)
    bs, per = stacked_batches(8, b1, node_cap=nc1c, edge_cap=ec1c, snug=True)
    run("dp8_coo", bs, per, mesh8, model_coo, model_coo,
        make_parallel_train_step(mesh8))

    nc2c, ec2c = capacities_for(graphs, b2, snug=True)
    ec2c = -(-ec2c // 2) * 2  # batches pack at exactly this shard-even cap
    bs, per = stacked_batches(4, b2, node_cap=nc2c, edge_cap=ec2c, snug=True)
    run("dp4xgp2_coo", bs, per, mesh2d, model_coo, model_coo_gp,
        make_dp_edge_parallel_train_step(mesh2d))

    d8 = result["dp8_dense"]["structs_per_sec_per_chip"]
    c8 = result["dp8_coo"]["structs_per_sec_per_chip"]
    dd = result["dp4xgp2_dense"]["structs_per_sec_per_chip"]
    dc = result["dp4xgp2_coo"]["structs_per_sec_per_chip"]
    result["layout_ratio_unsharded"] = round(d8 / c8, 4)
    result["layout_ratio_sharded"] = round(dd / dc, 4)
    result["sharding_overhead_dense"] = round(dd / d8, 4)
    result["sharding_overhead_coo"] = round(dc / c8, 4)
    result["tpu_reference"] = {
        "note": ("dense/COO on the REAL chip (unsharded, BENCH r4): 2.2x "
                 "MP / 1.7x force — the layout advantage the sharded "
                 "ratios above show is preserved under the graph axis"),
        "bench_r4_dense_vs_coo_mp": 2.2,
    }
    line = json.dumps(jsonfinite(result))
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
