#!/usr/bin/env bash
# Fleet chaos smoke (ISSUE 14 acceptance; .github/workflows/tier1.yml):
#
#  0. fleet.py entrypoint end to end: boot 2 replicas + the router
#     process, wait for fleet readiness, answer one /predict THROUGH
#     the router (X-Fleet-Replica header present), scrape the router's
#     /metrics (fleet_* counters + replica-labeled families), then
#     SIGTERM -> graceful fleet drain, exit 0.
#  1. KILL LEG: 3 replicas under open-loop load; kill -9 one replica
#     mid-load, restart it later. The loadgen hard-asserts (exit != 0
#     otherwise): ZERO lost accepted requests (in-flight work on the
#     dead replica retried onto survivors), EXACTLY ONE answer per
#     request (trace-id uniqueness + router duplicate counter 0), the
#     router actually saw transport errors (the chaos bit), and the
#     restarted replica was probed back in and answered again.
#     TRACE-JOIN SUB-LEG (ISSUE 15, --expect-trace-join): the kill must
#     additionally produce a flight-recorder bundle (the victim's
#     breaker trip fires it) whose joined Chrome trace holds >= 1
#     retried/hedged request with spans from >= 2 distinct processes —
#     the router's fleet.attempt spans nested over the replicas'
#     serve.request stage spans, pulled via each process's GET /trace.
#  2. PROMOTION LEG: a new checkpoint version committed mid-load rolls
#     across the fleet via each replica's own hot-reload watcher —
#     responses observed from BOTH versions, fleet converges
#     version-consistent, zero drops.
#  3. DEGRADED-REPLICA LEGS: (3a) one replica slowed by an injected
#     per-dispatch delay — the router must HEDGE past it (hedges > 0,
#     first success wins, straggler successes counted as waste, never
#     delivered); (3b) one replica failing dispatches (injected
#     exception -> typed 500) and dropping connections mid-request,
#     hedging disabled — the SEQUENTIAL retry + backoff path alone
#     must hold zero-lost (retries > 0, transport errors survived).
#  4. WEDGE LEG: a single replica with an injected WEDGED flush gets
#     SIGTERM; the bounded --drain-timeout must force-exit non-zero
#     with the unanswered count logged (a wedged flush must not hold
#     shutdown forever).
#  5. SLO LEG (ISSUE 16, --slo-report): an injected 5xx burst on one
#     replica (breaker effectively off so the burst is not quenched)
#     must walk the router's burn-rate alert inactive -> pending ->
#     firing -> resolved on second-scale rule windows AND dump a
#     flight-recorder bundle whose MANIFEST names the alert
#     (slo_burn_fleet_availability); plus the metrics-truth pins: the
#     router's /metrics/fleet histogram merge bit-identical to pooling
#     every replica's own scrape, the router's fleet latency histogram
#     count EXACTLY equal to answered requests, and its median in
#     agreement with the client-measured p50 within bucket resolution.
#  6. AUTOSCALE RAMP LEG (ISSUE 17, --autoscale --ramp): open-loop
#     load ramps low -> peak -> calm tail over 2 replicas while one
#     replica takes an injected preemption notice (exit75_at: SIGTERM
#     itself mid-load, drain, exit 75). The loadgen hard-asserts the
#     whole self-driving arc: the fleet GREW before any request was
#     shed (here: zero shed at all), SHRANK back on the calm tail
#     with zero lost accepted requests, the preempted replica's
#     announced exit was recorded as a SCALE EVENT (code 75, counted
#     in fleet_scale_events, breaker untouched) and NOT an incident
#     (fleet_incidents == 0, no flight-recorder bundle).
#  7. REMEDIATION WEDGE LEG (ISSUE 17, --remediate): one replica's
#     flush WEDGES mid-load (health plane keeps answering, dispatch
#     plane times out until the breaker trips). The breaker-trip
#     flight-recorder bundle must drive the remediator's
#     replace-and-drain — replacement routed from the warm pool,
#     victim unrouted (counted fleet_incidents) and force-reaped past
#     the drain bound — under continuing load with ZERO lost accepted
#     requests, and remediation.jsonl must name the justifying bundle.
#  8. CONTINUAL LOOP LEG (ISSUE 18, --continual): the full closed
#     loop under live load. Late ground-truth labels POST to the
#     router's /label and join the durable journal EXACTLY ONCE
#     (deliberate re-POSTs answer 'already'); a continual.py trainer
#     subprocess tails the journal and commits two candidates — a
#     clean round and a round trained on deliberately corrupted
#     labels (injected label_noise fault). The canary controller pins
#     one replica per candidate, mirrors labeled traffic to it, and
#     the gate PROMOTES the clean candidate fleet-wide (every
#     replica's gated reload watcher rolls it in; fleet converges,
#     zero drops) then ROLLS BACK the corrupted one, dumping a
#     flight-recorder bundle that names the regressing version.
#  9. MIXED-PRIORITY CHAOS LEG (ISSUE 19, --priority-mix): open-loop
#     interactive + scavenger load over 3 replicas; kill -9 one
#     replica mid-load and restart it later. The loadgen hard-asserts
#     the front-door contracts: the INTERACTIVE class's p99 holds its
#     --class-slo-ms bound straight through the kill (the capacity
#     loss lands on the scavenger class, which has no bound), zero
#     lost ACCEPTED requests, exactly-once answers, and backfilled
#     responses observed (scavengers riding interactive flushes'
#     padded slots on the replicas). Feasibility sheds
#     (infeasible_queue / infeasible_deadline) are ALLOWED here — they
#     are load shedding at admission, not loss (INVARIANTS.md).
# 10. ONE-FLEET-CACHE LEG (ISSUE 20, --zipf --kill-owner
#     --expect-cachepart): Zipf-distributed keyset over 3 replicas;
#     kill -9 the consistent-hash ring OWNER of the hottest cache key
#     mid-stampede, restart it later. Hard-asserts: the victim's arcs
#     re-own DETERMINISTICALLY to a ring successor while it is down
#     and revert on restart, zero lost accepted requests through the
#     owner loss, fleet-wide duplicate in-flight misses EXACTLY 0
#     (single-flight at router and replica), owner-affinity routing
#     engaged (fleet_owner_routed > 0), and the fleet's effective hit
#     ratio recovers (>= 50% post-restart) as the reborn owner's
#     cache re-warms. Ownership stays an optimization, never a
#     correctness dependency (INVARIANTS.md).
#
# Runs anywhere jax[cpu] does (synthetic data, CPU device).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT
BASE="${FLEET_SMOKE_PORT:-18460}"

echo "== setup: tiny synthetic checkpoint =="
python scripts/serve_loadgen.py --make-ckpt "$WORK/ckpt"

echo "== leg 0: fleet.py entrypoint (router + 2 replicas, drain) =="
python fleet.py "$WORK/ckpt" --replicas 2 --port "$BASE" \
  --replica-base-port "$((BASE + 1))" --log-dir "$WORK/fleet0-logs" \
  --serve-arg=--calibrate --serve-arg=64 \
  --trace-out "$WORK/fleet0_trace.json" \
  >"$WORK/fleet0.log" 2>&1 &
FPID=$!
for _ in $(seq 1 900); do
  curl -sf "http://127.0.0.1:$BASE/healthz" >/dev/null 2>&1 && break
  if ! kill -0 "$FPID" 2>/dev/null; then
    echo "fleet.py died during startup" >&2
    cat "$WORK/fleet0.log" >&2
    exit 1
  fi
  sleep 0.2
done
python - "$BASE" <<'EOF'
import json, sys, urllib.request
base = f"http://127.0.0.1:{sys.argv[1]}"
from cgnn_tpu.config import DataConfig
from cgnn_tpu.data.dataset import load_synthetic
g = load_synthetic(1, DataConfig(radius=6.0,
                                 max_num_nbr=12).featurize_config(),
                   seed=3)[0]
body = json.dumps({"graph": {
    "atom_fea": g.atom_fea.tolist(), "edge_fea": g.edge_fea.tolist(),
    "centers": g.centers.tolist(), "neighbors": g.neighbors.tolist(),
}, "timeout_ms": 30000}, allow_nan=False).encode()
req = urllib.request.Request(base + "/predict", data=body,
                             headers={"Content-Type": "application/json"})
with urllib.request.urlopen(req, timeout=60.0) as resp:
    payload = json.loads(resp.read())
    replica = resp.headers.get("X-Fleet-Replica")
    attempts = resp.headers.get("X-Fleet-Attempts")
assert payload.get("param_version"), payload
assert replica is not None and attempts == "1", (replica, attempts)
with urllib.request.urlopen(base + "/metrics", timeout=30.0) as resp:
    text = resp.read().decode()
from cgnn_tpu.observe.export import parse_prometheus_text
fams = parse_prometheus_text(text)
for prefix in ("cgnn_fleet_", "cgnn_replica_"):
    assert any(f.startswith(prefix) for f in fams), (prefix, sorted(fams))
with urllib.request.urlopen(base + "/healthz", timeout=10.0) as resp:
    health = json.loads(resp.read())
assert health["ready"] and health["replicas_ready"] == 2, health
# the on-demand fleet trace join (ISSUE 15): router + both replicas'
# span rings merged live; the routed predict above must appear as a
# trace spanning the router AND its answering replica
with urllib.request.urlopen(base + "/trace/joined", timeout=30.0) as resp:
    joined = json.loads(resp.read())
assert not joined.get("collect_errors"), joined.get("collect_errors")
pids = {e.get("pid") for e in joined["traceEvents"]
        if e.get("ph") != "M"}
assert len(pids) >= 2, ("joined trace covers one process", sorted(pids))
tid = payload["trace_id"]
assert tid in joined["traces"], (tid, sorted(joined["traces"])[:5])
assert len(joined["traces"][tid]["pids"]) >= 2, joined["traces"][tid]
print("leg 0 ok: routed predict via replica", replica,
      "-", len(fams), "metric families, fleet ready", health["versions"],
      "- joined trace:", len(joined["traces"]), "trace(s) over",
      len(pids), "processes")
EOF
kill -TERM "$FPID"
set +e; wait "$FPID"; RC=$?; set -e
if [ "$RC" -ne 0 ]; then
  echo "expected graceful fleet drain exit 0, got $RC" >&2
  tail -40 "$WORK/fleet0.log" >&2
  exit 1
fi
grep -q "fleet: drained" "$WORK/fleet0.log"
# --trace-out: one joined Perfetto file written at drain
python - "$WORK/fleet0_trace.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["traceEvents"], "empty joined trace"
assert doc["traces"], "no per-trace index in joined trace"
print("leg 0 drain ok: --trace-out wrote", len(doc["traces"]),
      "trace(s)")
EOF

echo "== leg 1: kill -9 a live replica mid-load, restart, re-admit =="
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --fleet 3 --fleet-base-port "$((BASE + 10))" \
  --fleet-log-dir "$WORK/fleet1-logs" \
  --clients 16 --duration 20 \
  --kill-at 0.3 --restart-at 0.5 --kill-replica 1 \
  --expect-retries --expect-trace-join --no-scrape \
  --report "$WORK/fleet_kill.json"
python - "$WORK/fleet_kill.json" <<'EOF'
import json, os, sys
r = json.load(open(sys.argv[1]))
assert not r["failures"], r["failures"]
assert r["dropped"] == 0 and not r["client_errors"], r
fl = r["fleet"]; rc = fl["router"]["counts"]; chaos = fl["chaos"]
assert "killed_at_s" in chaos and chaos["restart_ready"], chaos
assert rc["fleet_transport_errors"] >= 1, rc
assert rc["fleet_retries"] >= 1, rc
assert rc["fleet_duplicate_answers"] == 0, rc
assert chaos["victim_answered_at_end"] > chaos["victim_answered_at_restart"], chaos
t = r["tracing"]
assert t["unique_trace_ids"] == r["answered"] and t["missing_trace_ids"] == 0, t
# the ISSUE-15 trace-join sub-leg: joined fleet trace + incident bundle
obs = fl["observe"]
assert obs["windows"] >= 2, obs
assert obs["cross_process_requests"] >= 1, obs
assert obs["flightrec"]["bundles"] >= 1, obs
trig = obs["flightrec"]["triggers"]
assert ("breaker_trip" in trig or "replica_unreachable" in trig), trig
assert obs["bundle_cross_process_requests"] >= 1, obs
for f in ("trace.json", "requests.jsonl", "manifest.json",
          "metrics.json"):
    assert f in obs["bundle_files"], (f, obs["bundle_files"])
assert os.path.exists(obs["trace_joined"]), obs
print("leg 1 ok:", r["answered"], "answered @", r["throughput_rps"],
      "rps | kill at", chaos["killed_at_s"], "s, restart at",
      chaos["restarted_at_s"], "s | victim answered",
      chaos["victim_answered_at_restart"], "->",
      chaos["victim_answered_at_end"], "|", rc["fleet_retries"],
      "retries,", rc["fleet_transport_errors"], "transport errors - 0 lost |",
      obs["cross_process_requests"], "cross-process traces,",
      obs["flightrec"]["bundles"], "flightrec bundle(s)")
EOF

echo "== leg 2: rolling checkpoint promotion across the fleet =="
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --fleet 3 --fleet-base-port "$((BASE + 20))" \
  --fleet-log-dir "$WORK/fleet2-logs" \
  --clients 16 --duration 15 \
  --promote-at 0.4 --no-scrape \
  --report "$WORK/fleet_promote.json"
python - "$WORK/fleet_promote.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert not r["failures"], r["failures"]
assert r["dropped"] == 0, r
fl = r["fleet"]; chaos = fl["chaos"]
assert chaos.get("promotion_consistent"), chaos
versions = [v for v, c in r["param_versions"].items() if c > 0]
assert len(versions) >= 2, r["param_versions"]
final = set(chaos["final_versions"].values())
assert len(final) == 1 and chaos["promoted_to"] in final, chaos
print("leg 2 ok:", r["answered"], "answered across versions",
      r["param_versions"], "- fleet converged on", chaos["promoted_to"],
      "- 0 drops")
EOF

echo "== leg 3a: slow replica -> deadline-aware hedging =="
# hedging is the mechanism under test here, so it also ABSORBS the
# slow replica's failures before a sequential retry would fire — the
# retry path gets its own leg (3b) with hedging disabled
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --fleet 3 --fleet-base-port "$((BASE + 30))" \
  --fleet-log-dir "$WORK/fleet3a-logs" \
  --clients 16 --duration 12 \
  --replica-faults "slow_dispatch=150" --faulty-replica 2 \
  --hedge-ms 120 --expect-hedges \
  --report "$WORK/fleet_hedge.json"
python - "$WORK/fleet_hedge.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert not r["failures"], r["failures"]
assert r["dropped"] == 0, r
rc = r["fleet"]["router"]["counts"]
assert rc["fleet_hedges"] >= 1, rc
assert rc["fleet_duplicate_answers"] == 0, rc
t = r["tracing"]
assert t["unique_trace_ids"] == r["answered"], t
scrape = r["fleet"]["metrics_scrape"]
assert scrape["parse_ok"] and not scrape["missing_families"], scrape
print("leg 3a ok:", r["answered"], "answered |", rc["fleet_hedges"],
      "hedges (", rc.get("fleet_hedge_wins", 0), "wins,",
      rc.get("fleet_hedge_waste", 0), "waste ) - 0 lost,",
      "exactly-once held")
EOF

echo "== leg 3b: failing dispatch + dropped connections -> retries =="
# hedging OFF so the 500s (injected dispatch exception) and transport
# errors (every 25th connection closed mid-request) must be survived
# by the SEQUENTIAL retry + backoff path alone
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --fleet 3 --fleet-base-port "$((BASE + 35))" \
  --fleet-log-dir "$WORK/fleet3b-logs" \
  --clients 16 --duration 10 \
  --replica-faults "dispatch_exc=3;drop_conn=25" --faulty-replica 2 \
  --hedge-ms 0 --expect-retries --no-scrape \
  --report "$WORK/fleet_retry.json"
python - "$WORK/fleet_retry.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert not r["failures"], r["failures"]
assert r["dropped"] == 0, r
rc = r["fleet"]["router"]["counts"]
assert rc["fleet_retries"] >= 1, rc
assert rc["fleet_transport_errors"] >= 1, rc  # the dropped conns bit
assert rc["fleet_duplicate_answers"] == 0, rc
t = r["tracing"]
assert t["unique_trace_ids"] == r["answered"], t
print("leg 3b ok:", r["answered"], "answered |", rc["fleet_retries"],
      "retries over", rc["fleet_transport_errors"], "transport errors",
      "+", rc.get("fleet_upstream_500", 0), "upstream 500s - 0 lost")
EOF

echo "== leg 4: wedged flush vs bounded --drain-timeout (force exit) =="
PORT4=$((BASE + 40))
CGNN_TPU_FAULTS="wedge_flush=2:600" \
python serve.py "$WORK/ckpt" --port "$PORT4" --calibrate 64 \
  --drain-timeout 5 >"$WORK/wedge.log" 2>&1 &
WPID=$!
for _ in $(seq 1 600); do
  curl -sf "http://127.0.0.1:$PORT4/healthz" >/dev/null 2>&1 && break
  if ! kill -0 "$WPID" 2>/dev/null; then
    echo "serve.py died during startup" >&2
    cat "$WORK/wedge.log" >&2
    exit 1
  fi
  sleep 0.2
done
python - "$PORT4" <<'EOF'
import json, sys, threading, urllib.request
base = f"http://127.0.0.1:{sys.argv[1]}"
from cgnn_tpu.config import DataConfig
from cgnn_tpu.data.dataset import load_synthetic
# DISTINCT structures: identical bodies would be served from the LRU
# result cache after the first flush and never reach the wedge point
graphs = load_synthetic(6, DataConfig(radius=6.0,
                                      max_num_nbr=12).featurize_config(),
                        seed=4)
bodies = [json.dumps({"graph": {
    "atom_fea": g.atom_fea.tolist(), "edge_fea": g.edge_fea.tolist(),
    "centers": g.centers.tolist(), "neighbors": g.neighbors.tolist(),
}, "timeout_ms": 60000}, allow_nan=False).encode() for g in graphs]

def post(i):
    req = urllib.request.Request(base + "/predict", data=bodies[i],
                                 headers={"Content-Type":
                                          "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=8.0) as resp:
            resp.read()
    except Exception:
        pass  # requests 3+ hang on the wedged flush — expected

# sequential posts make each request its own flush: flushes 0 and 1
# answer, flush 2 WEDGES the dispatch worker for 600 s, the rest queue
threads = []
for i in range(6):
    t = threading.Thread(target=post, args=(i,), daemon=True)
    t.start(); threads.append(t)
    t.join(timeout=6.0)
print("wedge armed: requests issued")
EOF
kill -TERM "$WPID"
set +e; wait "$WPID"; RC=$?; set -e
if [ "$RC" -eq 0 ]; then
  echo "expected FORCED non-zero exit past --drain-timeout, got 0" >&2
  tail -40 "$WORK/wedge.log" >&2
  exit 1
fi
grep -q "drain timed out" "$WORK/wedge.log"
grep -q "unanswered" "$WORK/wedge.log"
grep -q "force-exiting" "$WORK/wedge.log"
echo "leg 4 ok: wedged drain force-exited rc=$RC with unanswered count logged"

echo "== leg 5: 5xx burst -> burn-rate alert -> evidence bundle =="
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --fleet 3 --fleet-base-port "$((BASE + 45))" \
  --fleet-log-dir "$WORK/fleet5-logs" \
  --clients 16 --duration 25 \
  --replica-faults "dispatch_exc=15:150" --faulty-replica 2 \
  --breaker-k 999 --hedge-ms 0 --expect-retries --no-scrape \
  --slo-report \
  --report "$WORK/fleet_slo.json"
python - "$WORK/fleet_slo.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert not r["failures"], r["failures"]
slo = r["fleet"]["slo"]
assert slo["merge_bitexact"], slo["merge_mismatches"]
assert "cgnn_serve_latency_ms_hist" in slo["hist_families"], slo
lt = slo["latency_truth"]
assert lt["count_exact"] and lt["count_covers_answered"], lt
assert lt["p50_agree"], lt
alert = slo["alert"]
assert "fired_at_s" in alert and "resolved_at_s" in alert, alert
assert alert["resolved_at_s"] > alert["fired_at_s"], alert
# the firing transition dumped an evidence bundle whose MANIFEST names
# the alert as its trigger reason — the ISSUE-16 page-as-bundle pin
assert slo["slo_bundles"], slo
b = slo["slo_bundles"][0]
assert b["reason"] == "slo_burn_fleet_availability", b
assert "burn_fast" in b["detail"], b
print("leg 5 ok:", r["answered"], "answered | alert fired",
      alert["fired_at_s"], "s, resolved", alert["resolved_at_s"],
      "s | fleet merge bit-exact over", len(slo["hist_families"]),
      "histogram families | router hist count", lt["hist_count"],
      "== answered, p50", lt["hist_p50_ms"], "~", lt["measured_p50_ms"],
      "ms | bundle:", b["bundle"])
EOF

echo "== leg 6: load ramp -> elastic autoscale + exit-75 preemption =="
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --fleet 2 --fleet-base-port "$((BASE + 50))" \
  --fleet-log-dir "$WORK/fleet6-logs" \
  --clients 16 --duration 30 --ramp 4:60 \
  --autoscale --min-replicas 2 --max-replicas 4 --warm-pool 1 \
  --replica-faults "exit75_at=15" --faulty-replica 1 \
  --no-scrape \
  --report "$WORK/fleet_ramp.json"
python - "$WORK/fleet_ramp.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert not r["failures"], r["failures"]
fl = r["fleet"]
rc = fl["router"]["counts"]
auto = fl["autoscale"]
counts = auto["counts"]
# the fleet grew under the ramp and shrank back on the calm tail
assert counts["scale_ups"] >= 1, counts
assert counts["scale_downs"] >= 1, counts
# grew before shedding — here strictly: it never shed at all
assert rc["fleet_shed"] == 0, rc
# the announced exits (injected exit-75 preemption + the autoscaler's
# own drained scale-downs) are SCALE EVENTS, never incidents
assert rc["fleet_scale_events"] >= 1, rc
assert rc["fleet_incidents"] == 0, rc
# the preempted replica delivered the resumable code, not a crash
assert fl["replica_exit_codes"][1] == 75, fl["replica_exit_codes"]
# every autoscaler-owned replica drained clean at teardown
assert all(c in (0, 75) for c in auto["exit_codes"].values()), auto
ups = [e for e in auto["events"] if e["action"] == "scale_up"]
downs = [e for e in auto["events"] if e["action"] == "scale_down"]
print("leg 6 ok:", r["answered"], "answered, 0 shed |",
      counts["scale_ups"], "up /", counts["scale_downs"], "down |",
      "first up @", round(ups[0]["t_s"], 1), "s, first down @",
      round(downs[0]["t_s"], 1), "s |", rc["fleet_scale_events"],
      "scale events, 0 incidents | preempt exit",
      fl["replica_exit_codes"][1])
EOF

echo "== leg 7: wedged flush -> flight-recorder-driven remediation =="
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --fleet 2 --fleet-base-port "$((BASE + 55))" \
  --fleet-log-dir "$WORK/fleet7-logs" \
  --clients 12 --duration 35 \
  --replica-faults "wedge_flush=25:600" --faulty-replica 1 \
  --remediate --warm-pool 1 --max-replicas 4 \
  --timeout-ms 5000 --hedge-ms 100 --no-scrape \
  --report "$WORK/fleet_wedge.json"
python - "$WORK/fleet_wedge.json" <<'EOF'
import json, os, sys
r = json.load(open(sys.argv[1]))
assert not r["failures"], r["failures"]
fl = r["fleet"]
rc = fl["router"]["counts"]
rem = fl["remediation"]
acts = rem["actions"]
assert acts, "remediator never acted"
a = acts[0]
# the action chain is auditable: the breaker-trip evidence bundle is
# named by the action that it justified
assert a["action"] == "replace_and_drain", a
assert a["replica"] == 1, a
assert a["bundle"], a
assert a["replacement"] is not None, a
# the replacement actually answered traffic
rbd = r["devices"]["responses_by_device"]
assert rbd.get(str(a["replacement"]), 0) > 0, (a, rbd)
# the victim is out of rotation; its removal counted an INCIDENT
# (remediation is a failure response, not elastic sizing)
assert str(a["replica"]) not in fl["router"]["replicas"], (
    list(fl["router"]["replicas"]))
assert rc["fleet_incidents"] >= 1, rc
# the journal on disk carries the same evidence chain
entries = [json.loads(line) for line in
           open(os.path.join(os.path.dirname(sys.argv[1]),
                             "remediation.jsonl"))]
assert entries and all(e["bundle"] for e in entries), entries
print("leg 7 ok:", r["answered"], "answered, 0 lost | replica",
      a["replica"], "->", a["replacement"], "|",
      len(entries), "journal entr(y/ies), evidence:",
      os.path.basename(a["bundle"]))
EOF

echo "== leg 8: labels -> trainer -> canary -> promote + rollback =="
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --fleet 3 --fleet-base-port "$((BASE + 60))" \
  --fleet-log-dir "$WORK/fleet8-logs" \
  --clients 8 --duration 45 --continual \
  --no-scrape \
  --report "$WORK/fleet_continual.json"
python - "$WORK/fleet_continual.json" <<'EOF'
import json, os, sys
r = json.load(open(sys.argv[1]))
assert not r["failures"], r["failures"]
fl = r["fleet"]
lb = fl["labels"]; js = lb["journal"]
# the exactly-once join ledger, over the wire
assert lb["sent"] >= 1 and lb["joined"] == lb["sent"], lb
assert lb["unmatched"] == 0 and lb["resend_not_already"] == 0, lb
assert js["duplicate_joins"] == lb["double_posts"], lb
assert js["served"] == r["answered"], (js, r["answered"])
cont = fl["continual"]
commits = cont["commits"]
assert len(commits) >= 2, cont
# the clean candidate promoted fleet-wide, zero drops while it rolled
assert cont["promoted"] == commits[0], cont
assert cont["promotion_consistent"], cont
assert r["param_versions"].get(cont["promoted"], 0) > 0, (
    r["param_versions"])
# the corrupted candidate refused: rolled back, bundle NAMES it
assert cont["rolled_back"] == commits[1], cont
assert cont["rollback_bundle"], cont
assert cont["rolled_back"] in os.path.basename(
    cont["rollback_bundle"]), cont
man = json.load(open(os.path.join(cont["rollback_bundle"],
                                  "manifest.json")))
assert cont["rolled_back"] in man["reason"], man
assert cont["trainer_exit"] in (0, 75), cont
print("leg 8 ok:", r["answered"], "answered |", lb["sent"],
      "labels joined exactly once (", lb["double_posts"],
      "re-POSTs all 'already' ) | candidates", commits, "|",
      cont["promoted"], "promoted fleet-wide,", cont["rolled_back"],
      "rolled back (", cont.get("rollback_reason"), ") | bundle:",
      os.path.basename(cont["rollback_bundle"]))
EOF

echo "== leg 9: mixed-priority load + kill -9 -> interactive p99 holds =="
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --fleet 3 --fleet-base-port "$((BASE + 65))" \
  --fleet-log-dir "$WORK/fleet9-logs" \
  --clients 16 --duration 25 \
  --priority-mix "interactive=12,scavenger=24" \
  --class-slo-ms "interactive=2500" \
  --class-wait-ms "interactive=8,scavenger=250" \
  --expect-backfill \
  --kill-at 0.35 --restart-at 0.55 --kill-replica 1 \
  --expect-retries --no-scrape \
  --report "$WORK/fleet_priority.json"
python - "$WORK/fleet_priority.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert not r["failures"], r["failures"]
assert r["dropped"] == 0 and not r["client_errors"], r
fl = r["fleet"]; rc = fl["router"]["counts"]; chaos = fl["chaos"]
assert "killed_at_s" in chaos and chaos["restart_ready"], chaos
assert rc["fleet_retries"] >= 1, rc
assert rc["fleet_duplicate_answers"] == 0, rc
t = r["tracing"]
assert t["unique_trace_ids"] == r["answered"] and t["missing_trace_ids"] == 0, t
pr = r["priority"]
by_cls = pr["latency_ms_by_class"]
# both classes made progress through the kill, and the head class's
# p99 held its bound while the scavenger class absorbed the slack
assert pr["responses_by_class"].get("interactive", 0) > 0, pr
assert pr["responses_by_class"].get("scavenger", 0) > 0, pr
assert by_cls["interactive"]["p99"] <= 2500.0, by_cls
# replicas converted interactive padding into scavenger answers
assert pr["backfilled_responses"] >= 1, pr
# the router classified traffic at the front door (per-class counters)
for c in ("interactive", "scavenger"):
    assert rc.get(f"fleet_class_{c}_requests", 0) > 0, rc
shed = {k: v for k, v in r["rejected"].items()
        if k in ("infeasible_queue", "infeasible_deadline")}
print("leg 9 ok:", r["answered"], "answered |",
      {c: n for c, n in sorted(pr["responses_by_class"].items())},
      "| interactive p99", round(by_cls["interactive"]["p99"], 1),
      "ms <= 2500 ms through the kill | scavenger p99",
      round(by_cls["scavenger"]["p99"], 1), "ms |",
      pr["backfilled_responses"], "backfilled |",
      rc["fleet_retries"], "retries - 0 lost |",
      "feasibility sheds:", shed or 0)
EOF

echo "== leg 10: Zipf keyset + kill -9 the cache OWNER mid-stampede =="
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --fleet 3 --fleet-base-port "$((BASE + 70))" \
  --fleet-log-dir "$WORK/fleet10-logs" \
  --clients 16 --duration 25 --structures 64 \
  --zipf 1.1 --kill-owner --kill-at 0.35 --restart-at 0.6 \
  --expect-cachepart --expect-retries --no-scrape \
  --report "$WORK/fleet_cachepart.json"
python - "$WORK/fleet_cachepart.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert not r["failures"], r["failures"]
# zero lost accepted requests through the owner kill
assert r["dropped"] == 0 and not r["client_errors"], r
fl = r["fleet"]; rc = fl["router"]["counts"]; chaos = fl["chaos"]
assert "killed_at_s" in chaos and chaos["restart_ready"], chaos
cp = chaos["cachepart"]
# the victim WAS the ring owner of the hottest key, its arcs re-owned
# to a survivor while it was down, and ownership reverted on restart
assert cp["owner_before"] == fl["victim"], (cp, fl["victim"])
assert cp["owner_during_kill"] not in (None, cp["owner_before"]), cp
assert cp["owner_after_restart"] == cp["owner_before"], cp
# owner-affinity actually routed, and single-flight held the
# duplicate-in-flight-miss count at EXACTLY zero fleet-wide
assert rc["fleet_fingerprinted"] > 0 and rc["fleet_owner_routed"] > 0, rc
end = cp["counters_at_end"]
assert end["cache_dup_misses"] == 0, end
# hit-ratio recovery after the restart (asserted inside the loadgen
# too; recompute here so the leg's evidence is self-contained)
base = cp["counters_at_restart"]
d_req = end["requests"] - base["requests"]
d_hit = (end["cache_hits"] + end["cache_coalesced"]
         - base["cache_hits"] - base["cache_coalesced"])
assert d_req > 0 and d_hit / d_req >= 0.5, (base, end)
t = r["tracing"]
assert t["unique_trace_ids"] == r["answered"] and t["missing_trace_ids"] == 0, t
print("leg 10 ok:", r["answered"], "answered - 0 lost | owner",
      cp["owner_before"], "->", cp["owner_during_kill"],
      "(kill) ->", cp["owner_after_restart"], "(restart) |",
      "post-restart hit ratio",
      round(d_hit / d_req, 3), "over", d_req, "requests |",
      end["cache_dup_misses"], "dup misses |",
      rc["fleet_owner_routed"], "owner-routed,",
      rc.get("fleet_peer_fills", 0), "peer fills")
EOF

echo "fleet smoke: ALL LEGS PASSED"
