#!/usr/bin/env bash
# Serving smoke (ISSUE 3 acceptance; .github/workflows/tier1.yml):
#
#  1. in-process load: 64 concurrent clients against the micro-batching
#     server on a tiny synthetic checkpoint, with a checkpoint hot-swap
#     committed mid-load -> the loadgen itself asserts ZERO dropped
#     responses, ZERO recompiles after warmup, and responses observed
#     from BOTH param versions (exit non-zero otherwise);
#     1b forces the compact+pipelined ingest (ISSUE 4); 1c forces the
#     thread-per-device dispatch layer across 8 virtual host devices
#     (ISSUE 5: distribution + per-replica swap consistency); 1f runs
#     the same dryrun through the MESH engine (ISSUE 10: one
#     batch-sharded dispatch covers all 8 devices, compile count =
#     programs not programs x 8, shard-level distribution + swap
#     consistency); 1d reruns
#     the 64-client load under CGNN_TPU_RACECHECK=1 (ISSUE 7) and
#     asserts ZERO lock-order inversions, ZERO unguarded shared-field
#     accesses, and ZERO deadlock-watchdog dumps;
#  2. HTTP front-end: start serve.py, wait for /healthz, fire concurrent
#     HTTP requests, then SIGTERM -> the server must drain gracefully
#     (queued requests answered) and exit 0. ISSUE 6 adds the
#     metrics-scrape leg MID-LOAD: GET /metrics must parse as Prometheus
#     exposition format with the serve_*/device*/pipeline_* families
#     present, and POST /profile must complete a bounded on-demand
#     device-trace capture with a non-empty artifact while traffic
#     keeps flowing (concurrent captures are rejected 409, not stacked).
#     Leg 1 additionally turns the full tracing plane on (--telemetry
#     epoch --profile-mid): every response must carry a distinct trace
#     id, the X-Request-Id probe must echo, and the scraped rolling p99
#     must agree with the loadgen's own measurement.
#
# Runs anywhere jax[cpu] does (synthetic data, CPU device).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT
PORT="${SERVE_SMOKE_PORT:-18437}"

echo "== setup: tiny synthetic checkpoint =="
python scripts/serve_loadgen.py --make-ckpt "$WORK/ckpt"

echo "== leg 1: 64-client in-process load + hot swap + live plane =="
# --telemetry epoch turns the full tracing/export plane on (span
# stream + registry + rolling quantiles); --profile-mid fires one gated
# device-trace capture mid-load. The loadgen's own failure checks cover
# the new invariants (trace ids on every response, X-Request-Id probe
# echo, scraped-vs-measured p99 agreement, non-empty profile artifact).
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --clients 64 --duration 8 --hot-swap \
  --telemetry epoch --telemetry-dir "$WORK/obs" --profile-mid \
  --report "$WORK/slo_report.json"
python - "$WORK/slo_report.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["dropped"] == 0, r
assert r["compiles"]["after_warm"] == 0, r["compiles"]
assert len(r["param_versions"]) >= 2, r["param_versions"]
assert not r["failures"], r["failures"]
t = r["tracing"]
assert t["missing_trace_ids"] == 0 and t["probe_trace_id"] == "loadgen-probe-1", t
assert r["metrics_scrape"]["parse_ok"] and r["metrics_scrape"]["agree"], (
    r["metrics_scrape"])
assert r["profile"]["ok"] and r["profile"]["bytes"] > 0, r["profile"]
print("leg 1 ok:", r["answered"], "answered @", r["throughput_rps"], "rps,",
      "p99", round(r["latency_ms"]["p99"], 1), "ms, versions",
      list(r["param_versions"]), "| scrape p99",
      round(r["metrics_scrape"]["scraped_p99_ms"], 1), "ms | profile",
      r["profile"]["bytes"], "bytes |", t["unique_trace_ids"], "trace ids")
EOF
python - "$WORK/obs/trace.json" <<'EOF'
import json, sys
# the span-chain acceptance pin: at least one non-cached request span
# whose flush id joins to pack AND dispatch hop spans
doc = json.load(open(sys.argv[1]))
ev = doc["traceEvents"]
by_flush = {}
for e in ev:
    fid = e.get("args", {}).get("flush_id")
    if fid:
        by_flush.setdefault(fid, set()).add(e["name"])
chains = [f for f, names in by_flush.items()
          if {"serve.request", "serve.pack", "serve.dispatch"} <= names]
assert chains, f"no full request->pack->dispatch chain in trace: {by_flush}"
print("leg 1 trace ok:", len(chains), "flushes with full span chains")
EOF

echo "== leg 1b: compact-staged + pipelined packer (forced; ISSUE 4) =="
# CPU CI would never pick these under 'auto' (accelerator-only default),
# so force them: the SLO invariants — zero drops, zero recompiles after
# the doubled warmup (compact + full program per rung) — must hold under
# the new ingest machinery no matter the backend
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --clients 64 --duration 6 --compact on --pack-workers 2 \
  --report "$WORK/slo_compact.json"
python - "$WORK/slo_compact.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["dropped"] == 0, r
assert r["compiles"]["after_warm"] == 0, r["compiles"]
assert not r["failures"], r["failures"]
ingest = r["server_stats"]["ingest"]
assert ingest["compact"] and ingest["pack_workers"] == 2, ingest
assert r["server_stats"]["counts"].get("pack_compact", 0) > 0, (
    r["server_stats"]["counts"])
print("leg 1b ok:", r["answered"], "answered @", r["throughput_rps"],
      "rps under compact+pipelined ingest")
EOF

echo "== leg 1c: thread-per-device dispatch, 8 host devices (ISSUE 5) =="
# the MULTICHIP dryrun pattern: 8 virtual CPU devices + a FORCED
# --devices 8 ('auto' is deliberately single-device on CPU backends).
# --engine threads pins the ISSUE-5 DeviceSet layer explicitly (the
# default engine for a multi-device set is 'mesh' since ISSUE 10 — leg
# 1f covers it). Hard invariants: zero drops, zero recompiles after the
# N-device warmup (compile count = shapes x forms x 8, all at warmup),
# EVERY device answers responses, and a mid-load hot swap serves both
# param versions with each response's version consistent with its
# replica.
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --clients 64 --duration 6 --hot-swap --devices 8 --engine threads \
  --report "$WORK/slo_multidev.json"
python - "$WORK/slo_multidev.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["dropped"] == 0, r
assert r["compiles"]["after_warm"] == 0, r["compiles"]
assert not r["failures"], r["failures"]
dev = r["devices"]
assert dev["engine"] == "threads", dev
assert dev["count"] == 8, dev
silent = [i for i in range(8)
          if not dev["responses_by_device"].get(str(i))]
assert not silent, f"devices {silent} answered nothing: {dev}"
assert len(r["param_versions"]) >= 2, r["param_versions"]
print("leg 1c ok:", r["answered"], "answered across", dev["count"],
      "devices", dev["responses_by_device"], "- swap versions",
      list(r["param_versions"]))
EOF

echo "== leg 1f: mesh single-dispatch engine, 8 host devices (ISSUE 10) =="
# the SAME dryrun through the mesh execution layer (the default engine
# for a multi-device set): one batch-sharded jitted dispatch covers all
# 8 devices. Beyond leg 1c's invariants, the decisive pin is the
# compile count: at_warm must equal programs (rungs x staging forms),
# NOT programs x 8 — one multi-device executable per program.
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --clients 64 --duration 6 --hot-swap --devices 8 --engine mesh \
  --report "$WORK/slo_mesh.json"
python - "$WORK/slo_mesh.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["dropped"] == 0, r
assert r["compiles"]["after_warm"] == 0, r["compiles"]
assert not r["failures"], r["failures"]
dev = r["devices"]
assert dev["engine"] == "mesh", dev
assert dev["count"] == 8, dev
shapes = len(r["server_stats"]["shapes"])
# THE mesh pin: compile count = programs (one sharded executable per
# rung x staging form), never programs x devices
assert r["compiles"]["at_warm"] == shapes, (
    f"mesh warmup compiled {r['compiles']['at_warm']} programs for "
    f"{shapes} rungs - expected exactly one per rung, not per device")
silent = [i for i in range(8)
          if not dev["responses_by_device"].get(str(i))]
assert not silent, f"shards {silent} answered nothing: {dev}"
assert len(r["param_versions"]) >= 2, r["param_versions"]
print("leg 1f ok:", r["answered"], "answered across", dev["count"],
      "mesh shards", dev["responses_by_device"], "-",
      r["compiles"]["at_warm"], "compiles for", shapes, "rungs - swap",
      list(r["param_versions"]))
EOF

echo "== leg 1d: racecheck under the 64-client load (ISSUE 7) =="
# CGNN_TPU_RACECHECK=1 swaps every serve/pipeline/telemetry lock for the
# instrumented layer (cgnn_tpu/analysis/racecheck.py): lock-order
# recording, the shared-field tripwire on the server's counters, and the
# deadlock watchdog over the heartbeating dispatch/pack/watcher threads.
# The loadgen folds racecheck.report() into the SLO report and already
# exits non-zero on any inversion/violation/dump; the reader below pins
# the report SHAPE too (enabled, clean, heartbeats actually registered).
CGNN_TPU_RACECHECK=1 python scripts/serve_loadgen.py "$WORK/ckpt" \
  --clients 64 --duration 6 --hot-swap \
  --report "$WORK/slo_racecheck.json"
python - "$WORK/slo_racecheck.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["dropped"] == 0, r
assert not r["failures"], r["failures"]
rc = r["racecheck"]
assert rc["enabled"], "racecheck gate did not engage"
assert rc["inversions"] == [], rc["inversions"]
assert rc["violations"] == [], rc["violations"]
assert rc["deadlock_dumps"] == 0 and not rc["stalled_threads"], rc
assert rc["clean"], rc
assert rc["heartbeats_seen"], (
    "no thread ever heartbeated — the watchdog is watching nothing, "
    "which would make 'zero deadlocks' vacuous (heartbeats_seen, not "
    "heartbeating_threads: live beats race clean post-drain exits)")
print("leg 1d ok:", r["answered"], "answered under racecheck — 0",
      "inversions / 0 violations / 0 dumps across",
      len(rc["heartbeats_seen"]), "heartbeating threads:",
      rc["heartbeats_seen"])
EOF

echo "== leg 1e: mixed precision tiers under load (ISSUE 9) =="
# the server warms f32 + bf16 + int8 programs for every rung; each
# request draws a tier uniformly, so the batcher's tier-boundary flush
# cut runs constantly. Invariants: zero drops, ZERO recompiles after
# warmup (a tier that slipped past warm() would trace mid-load), and
# every requested tier actually answered.
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --clients 32 --duration 6 --precision f32,bf16,int8 \
  --report "$WORK/slo_precision.json"
python - "$WORK/slo_precision.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["dropped"] == 0, r
assert r["compiles"]["after_warm"] == 0, r["compiles"]
assert not r["failures"], r["failures"]
by_tier = r["precision"]["responses_by_tier"]
assert set(by_tier) == {"f32", "bf16", "int8"}, by_tier
assert all(v > 0 for v in by_tier.values()), by_tier
print("leg 1e ok:", r["answered"], "answered across tiers", by_tier,
      "- 0 drops / 0 recompiles")
EOF

echo "== leg 1g: raw-wire ingest under load (ISSUE 11) =="
# mixed raw/featurized traffic against a raw-wire server (forced: CPU
# 'auto' keeps raw off — the host IS the device). Invariants: zero
# drops, ZERO recompiles after warmup (raw programs warmed per rung
# like every other form), BOTH wires answered (the batcher's
# form-boundary cut runs constantly), the raw-vs-featurized parity
# probe agrees to f32 roundoff, and the --raw-overflow-probe leg
# proves the IN-PROGRAM cap-overflow flag end to end: a tiny cell
# needing more periodic images than the calibrated caps slips past
# the (disabled) host pre-check, the compiled program flags it, and
# the featurized fallback answers it — never the truncated graph.
python scripts/serve_loadgen.py "$WORK/ckpt" \
  --clients 32 --duration 6 --wire mixed --raw-overflow-probe \
  --report "$WORK/slo_rawwire.json"
python - "$WORK/slo_rawwire.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["dropped"] == 0, r
assert r["compiles"]["after_warm"] == 0, r["compiles"]
assert not r["failures"], r["failures"]
w = r["wire"]["responses_by_wire"]
assert w.get("raw") and w.get("featurized"), w
p = r["wire"]["probes"]
assert p["parity"]["ok"] and p["parity"]["max_abs_diff"] < 1e-3, p
assert p["overflow"]["ok"] and p["overflow"]["wire"] == "featurized", p
ing = r["server_stats"]["ingest"]
assert ing["raw"] and ing["cap_overflows"] >= 1, ing
assert ing["rung_edge_occupancy"], ing
print("leg 1g ok:", r["answered"], "answered across wires", w,
      "- parity", p["parity"]["max_abs_diff"], "- overflow fallback",
      ing["cap_overflows"], "- rung occupancy",
      ing["rung_edge_occupancy"], "- 0 drops / 0 recompiles")
EOF

echo "== leg 2: HTTP front-end + graceful SIGTERM drain =="
# --wire raw: the HTTP leg doubles as the raw-wire wire-path smoke —
# structure payloads admit straight into the in-program search
python serve.py "$WORK/ckpt" --port "$PORT" --calibrate 64 --wire raw \
  >"$WORK/serve.log" 2>&1 &
SPID=$!
for _ in $(seq 1 600); do
  curl -sf "http://127.0.0.1:$PORT/healthz" >/dev/null 2>&1 && break
  if ! kill -0 "$SPID" 2>/dev/null; then
    echo "serve.py died during startup" >&2
    cat "$WORK/serve.log" >&2
    exit 1
  fi
  sleep 0.2
done
curl -sf "http://127.0.0.1:$PORT/healthz" >/dev/null

# the HTTP loadgen itself scrapes GET /metrics and POSTs /profile
# MID-LOAD (the wire-path metrics-scrape leg); run it, then re-validate
# the exposition format + required families with an independent curl
# while the server is still up
python scripts/serve_loadgen.py --http "http://127.0.0.1:$PORT" \
  --clients 8 --duration 6 --profile-mid --wire mixed \
  --report "$WORK/slo_http.json"

echo "== leg 2b: metrics-scrape (exposition format + families) =="
curl -sf "http://127.0.0.1:$PORT/metrics" > "$WORK/metrics.prom"
python - "$WORK/metrics.prom" <<'EOF'
import sys
sys.path.insert(0, ".")
from cgnn_tpu.observe.export import parse_prometheus_text
fams = parse_prometheus_text(open(sys.argv[1]).read())
for prefix in ("cgnn_serve_", "cgnn_device", "cgnn_pipeline_"):
    present = [f for f in fams if f.startswith(prefix)]
    assert present, f"no {prefix}* family in /metrics: {sorted(fams)}"
assert fams["cgnn_serve_responses_total"]["samples"][0][1] > 0, (
    "no responses counted by scrape time")
print("leg 2b ok:", len(fams), "metric families, exposition format parses")
EOF

kill -TERM "$SPID"
set +e; wait "$SPID"; RC=$?; set -e
if [ "$RC" -ne 0 ]; then
  echo "expected graceful drain exit 0, got $RC" >&2
  tail -30 "$WORK/serve.log" >&2
  exit 1
fi
grep -q "draining" "$WORK/serve.log"
python - "$WORK/slo_http.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["answered"] > 0, "HTTP leg answered nothing"
assert not r["failures"], r["failures"]
# raw wire over the wire: structure payloads must have been answered by
# the in-program search (response "wire": "raw"), graph payloads by the
# featurized programs — mixed traffic, zero recompiles by construction
w = r["wire"]["responses_by_wire"]
assert w.get("raw") and w.get("featurized"), w
t = r["tracing"]
assert t["missing_trace_ids"] == 0, t
assert t["probe_trace_id"] == "loadgen-probe-1", t
s = r["metrics_scrape"]
assert s["parse_ok"] and not s["missing_families"], s
p = r["profile"]
assert p.get("ok") and p.get("bytes", 0) > 0, p
print("leg 2 ok:", r["answered"], "HTTP responses @",
      r["throughput_rps"], "rps | mid-load /metrics",
      s["text_bytes"], "bytes | /profile", p["bytes"], "bytes")
EOF

echo "serve smoke: ALL LEGS PASSED"
