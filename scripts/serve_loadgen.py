#!/usr/bin/env python
"""Concurrent open-loop load generator for the serving subsystem.

Drives the IN-PROCESS ``InferenceServer`` (no sockets — the pure core,
so CI and laptops measure batching/reload behavior, not TCP noise), a
running HTTP server (``--http URL``), or a whole REPLICA FLEET
(``--fleet N``, ISSUE 14: N real serve.py processes behind the
in-process FleetRouter, with kill -9/restart/rolling-promotion chaos
legs and the zero-lost-accepted + exactly-one-answer invariants
hard-asserted), and writes an SLO report JSON:
latency p50/p95/p99, throughput, batch occupancy, reject counts, param
versions observed, and the invariant checks the ISSUE pins:

- ZERO dropped responses: every submitted request resolves (result or
  an explicit rejection — never a hung future);
- ZERO recompiles after warmup (the jit cache-miss counter is read
  before and after the run);
- a mid-run checkpoint hot-swap (``--hot-swap``) completes with both
  param versions observed in responses and zero drops — in-flight
  requests finish on the old params.

Exit code is non-zero when any pinned invariant fails, so CI can run
this directly (tier1.yml serve-smoke).

Typical use::

    python scripts/serve_loadgen.py --make-ckpt /tmp/serve-ckpt
    python scripts/serve_loadgen.py /tmp/serve-ckpt --clients 64 \
        --duration 10 --hot-swap --report slo.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("ckpt_dir", nargs="?", default=None,
                   help="checkpoint directory (see --make-ckpt)")
    p.add_argument("--make-ckpt", metavar="DIR", default="",
                   help="create a tiny synthetic checkpoint at DIR and exit")
    p.add_argument("--http", default="",
                   help="fire at a running HTTP server instead of in-process")
    # ---- fleet chaos mode (ISSUE 14) ----
    p.add_argument("--fleet", type=int, default=0, metavar="N",
                   help="spawn N serve.py replica processes + the "
                        "in-process FleetRouter and drive open-loop "
                        "load THROUGH the router (cgnn_tpu/fleet/); "
                        "hard-asserts zero lost accepted requests and "
                        "exactly one answer per request — the chaos "
                        "legs below kill/restart live replicas "
                        "underneath the load")
    p.add_argument("--fleet-base-port", type=int, default=18460)
    p.add_argument("--fleet-log-dir", default="",
                   help="per-replica log files (default: next to "
                        "--report)")
    p.add_argument("--kill-at", type=float, default=0.0, metavar="FRAC",
                   help="kill -9 the victim replica at FRAC of the "
                        "load duration (0 disables) — in-flight "
                        "requests must be retried onto survivors, "
                        "zero lost")
    p.add_argument("--restart-at", type=float, default=0.0,
                   metavar="FRAC",
                   help="restart the killed replica at FRAC of the "
                        "duration; the router must probe it back in "
                        "and it must answer again (asserted)")
    p.add_argument("--kill-replica", type=int, default=1,
                   help="victim replica index for --kill-at")
    # ---- one fleet cache (ISSUE 20) ----
    p.add_argument("--zipf", type=float, default=0.0, metavar="S",
                   help="Zipf exponent for the request keyset (0 = "
                        "uniform): body i drawn with p ~ 1/(i+1)^S, so "
                        "body 0 is the hottest key — the distribution "
                        "the partitioned fleet cache is built for")
    p.add_argument("--kill-owner", action="store_true",
                   help="pick the --kill-at victim dynamically: the "
                        "cache-ring OWNER of the hottest key "
                        "(overrides --kill-replica; the ring is "
                        "deterministic, so the choice is reproducible)")
    p.add_argument("--expect-cachepart", action="store_true",
                   help="hard-assert the one-fleet-cache invariants: "
                        "owner-affinity routing engaged, zero "
                        "duplicate in-flight misses fleet-wide, "
                        "deterministic re-ownership around the kill, "
                        "and post-restart hit-ratio recovery")
    p.add_argument("--promote-at", type=float, default=0.0,
                   metavar="FRAC",
                   help="commit a NEW checkpoint version at FRAC of "
                        "the duration: every replica's own watcher "
                        "rolls it in mid-load — both versions must "
                        "answer and the fleet must converge "
                        "version-consistent with zero drops (asserted)")
    p.add_argument("--replica-faults", default="", metavar="SPEC",
                   help="CGNN_TPU_FAULTS plan injected into ONE "
                        "replica (--faulty-replica), e.g. "
                        "'slow_dispatch=150' for the hedging leg or "
                        "'dispatch_exc=5' for the 500-retry leg")
    p.add_argument("--faulty-replica", type=int, default=2)
    # ---- closed-loop continual learning (ISSUE 18) ----
    p.add_argument("--label-feedback", type=float, default=0.0,
                   metavar="P",
                   help="fleet mode (ISSUE 18): POST late ground-truth "
                        "labels for this fraction of answered requests "
                        "through the router's /label wire surface "
                        "(--label-delay-ms behind each answer). The "
                        "exactly-once join ledger is hard-asserted: "
                        "every label joins its served record, "
                        "deliberate re-POSTs answer 'already', nothing "
                        "goes unmatched")
    p.add_argument("--label-delay-ms", type=float, default=250.0,
                   help="how far behind each answer its label arrives")
    p.add_argument("--continual", action="store_true",
                   help="fleet mode (ISSUE 18): close the loop — a "
                        "continual.py trainer subprocess tails the "
                        "durable label journal and commits candidate "
                        "checkpoints (round 2 deliberately corrupted "
                        "by a label_noise fault); the canary "
                        "controller pins one replica per candidate, "
                        "shadow-evaluates it on mirrored labeled "
                        "traffic, promotes the good candidate "
                        "fleet-wide through the gated reload watchers "
                        "and rolls the bad one back with a "
                        "flight-recorder bundle naming it. Implies "
                        "--label-feedback 1.0 unless set; all of it "
                        "hard-asserted")
    # ---- the self-driving fleet (ISSUE 17) ----
    p.add_argument("--ramp", default="", metavar="LOW:PEAK",
                   help="fleet mode (ISSUE 17): open-loop fleet-total "
                        "request rate in rps — holds LOW, climbs to "
                        "PEAK by mid-duration, then drops to a calm "
                        "tail. With --autoscale the self-driving "
                        "invariants are hard-asserted: the fleet grew "
                        "BEFORE any request was shed on the way up and "
                        "shrank with zero lost accepted on the way down")
    p.add_argument("--autoscale", action="store_true",
                   help="fleet mode (ISSUE 17): run the SLO-signal-"
                        "driven autoscaler over the replica set "
                        "(hysteresis decision core, prewarmed spare "
                        "pool, drain-then-reap scale-down); drained "
                        "exits must be recorded as scale events, never "
                        "incidents (asserted)")
    p.add_argument("--min-replicas", type=int, default=1,
                   help="autoscaler lower bound")
    p.add_argument("--max-replicas", type=int, default=4,
                   help="autoscaler upper bound")
    p.add_argument("--warm-pool", type=int, default=1,
                   help="pre-compiled unrouted spares kept warm "
                        "(prewarmed before load, so a scale-up is a "
                        "routing-table add, not a cold boot)")
    p.add_argument("--remediate", action="store_true",
                   help="fleet mode (ISSUE 17): attach the flight-"
                        "recorder-driven remediator; a wedged replica "
                        "(--replica-faults wedge_flush=N — health "
                        "plane answers, dispatch plane trips its "
                        "breaker) must be replaced-and-drained with "
                        "zero lost accepted, and every action's "
                        "remediation.jsonl entry must name the "
                        "evidence bundle that justified it (asserted; "
                        "needs --trace-ring > 0)")
    p.add_argument("--retries", type=int, default=3,
                   help="fleet router max extra attempts per request")
    p.add_argument("--hedge-ms", type=float, default=None,
                   help="fleet hedge point in ms (default auto: 2x "
                        "replica rolling p99; 0 disables)")
    p.add_argument("--breaker-k", type=int, default=3)
    p.add_argument("--breaker-cooldown", type=float, default=2.0)
    p.add_argument("--expect-hedges", action="store_true",
                   help="fail unless the router actually hedged (the "
                        "slow-replica leg)")
    p.add_argument("--expect-retries", action="store_true",
                   help="fail unless the router actually retried (the "
                        "kill / dispatch-exception legs)")
    p.add_argument("--expect-trace-join", action="store_true",
                   help="fleet mode (ISSUE 15): hard-assert the "
                        "cross-process observability layer — the "
                        "joined fleet trace must contain >= 1 "
                        "retried/hedged request with spans from >= 2 "
                        "processes, AND a flight-recorder bundle must "
                        "exist whose own joined trace shows the same "
                        "(the kill/hedge chaos legs set this)")
    p.add_argument("--trace-ring", type=int, default=65536, metavar="N",
                   help="span-ring size for the server/router under "
                        "test (0 disables the cross-process trace "
                        "layer — the PERF.md §18 A/B baseline)")
    p.add_argument("--slo-report", action="store_true",
                   help="fleet mode (ISSUE 16): run the metrics-truth "
                        "leg — second-scale burn-rate rules on the "
                        "router's SLO engine; an injected 5xx burst "
                        "(--replica-faults 'dispatch_exc=START:COUNT', "
                        "pair with a high --breaker-k so the burst is "
                        "not breaker-quenched) must walk the alert "
                        "inactive -> pending -> firing -> resolved AND "
                        "dump a flight-recorder bundle whose manifest "
                        "names the alert; plus the fleet histogram "
                        "truth check: the router's /metrics/fleet "
                        "merge must be bit-identical to merging every "
                        "replica's own scrape, cover every answered "
                        "request, and agree with the client-measured "
                        "latency distribution (all hard-asserted)")
    p.add_argument("--clients", type=int, default=64)
    p.add_argument("--duration", type=float, default=10.0,
                   help="seconds of open-loop load")
    p.add_argument("--rate", type=float, default=0.0,
                   help="per-client requests/sec (0 = closed-loop as fast "
                        "as responses return)")
    # ---- mixed-priority open-loop load (ISSUE 19) ----
    p.add_argument("--priority-mix", default="", metavar="SPEC",
                   help="per-class open-loop arrival rates as "
                        "'interactive=40,scavenger=60' (total "
                        "requests/s across the client pool; classes "
                        "absent from the spec send nothing). Each "
                        "request draws its class rate-weighted; the "
                        "report breaks latency down per class")
    p.add_argument("--class-slo-ms", default="", metavar="SPEC",
                   help="HARD per-class p99 SLOs as 'interactive=250': "
                        "the run fails (exit != 0) when a class's "
                        "measured p99 exceeds its bound (needs "
                        "--priority-mix)")
    p.add_argument("--class-timeout-ms", default="", metavar="SPEC",
                   help="per-class request deadlines (classes absent "
                        "fall back to --timeout-ms)")
    p.add_argument("--class-wait-ms", default="", metavar="SPEC",
                   help="per-class batcher wait budgets, passed to the "
                        "in-proc server / every fleet replica")
    p.add_argument("--tenants", default="", metavar="SPEC",
                   help="WFQ tenants as 'name=weight,...': each request "
                        "carries a uniformly-drawn tenant; the weights "
                        "ride to the in-proc server / fleet replicas")
    p.add_argument("--no-backfill", action="store_true",
                   help="disable padding-slack backfill on the in-proc "
                        "server / fleet replicas (the A/B baseline)")
    p.add_argument("--expect-backfill", action="store_true",
                   help="fail unless lower-class responses actually "
                        "rode a higher-class flush's padding slack")
    p.add_argument("--structures", type=int, default=512,
                   help="distinct synthetic structures to draw requests from")
    p.add_argument("--timeout-ms", type=float, default=30000.0,
                   help="per-request deadline handed to the server")
    p.add_argument("--hot-swap", action="store_true",
                   help="commit a new checkpoint at half-duration and "
                        "assert a zero-drop version transition")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--rungs", type=int, default=3)
    p.add_argument("--compact", choices=["auto", "on", "off"],
                   default="auto",
                   help="compact-staged serving (ISSUE 4): auto = "
                        "accelerator backends only; on/off force the "
                        "A/B legs")
    p.add_argument("--wire", choices=["featurized", "raw", "mixed"],
                   default="featurized",
                   help="request wire format (ISSUE 11): 'raw' submits "
                        "wire-form (positions, lattice, species) "
                        "structures — the server's in-program neighbor "
                        "search builds the graph; 'mixed' draws "
                        "raw/featurized 50:50 per request (exercises "
                        "the batcher's form-boundary cut). Both force "
                        "raw-wire serving on (CPU CI never picks it "
                        "under 'auto'). The report breaks responses "
                        "down per wire and HARD-ASSERTS zero "
                        "in-program cap overflows on the calibrated "
                        "ladder (unless --raw-overflow-probe)")
    p.add_argument("--raw-overflow-probe", action="store_true",
                   help="disable the host image-cap pre-check and "
                        "submit one tiny-cell structure that the "
                        "IN-PROGRAM overflow flag must catch and route "
                        "to the featurized fallback (asserted); "
                        "in-proc raw/mixed modes only")
    p.add_argument("--pack-workers", type=int, default=None,
                   help="server pack pipeline threads (0 = in-line pack, "
                        "the pre-ISSUE-4 worker; default follows the "
                        "backend like --compact auto)")
    p.add_argument("--devices", default="auto", metavar="{auto,N}",
                   help="device-parallel dispatch set (ISSUE 5): 'auto' "
                        "= all local devices on accelerators, one on "
                        "CPU; an integer forces that many anywhere. "
                        "With a forced N > 1 the loadgen HARD-ASSERTS "
                        "that every device answered responses")
    p.add_argument("--engine", choices=["auto", "mesh", "threads"],
                   default="auto",
                   help="multi-device execution layer (ISSUE 10): 'mesh' "
                        "(the auto default with >1 device) = one "
                        "batch-sharded jitted dispatch covers all "
                        "devices, device_id = the shard that computed "
                        "the row; 'threads' = the ISSUE-5 per-device "
                        "dispatch threads. The per-device "
                        "answered/version hard asserts apply to BOTH — "
                        "under mesh they read the shard-level stats")
    p.add_argument("--precision", default="f32", metavar="TIERS",
                   help="comma-separated precision tiers (f32,bf16,int8): "
                        "the server warms ALL of them, each request "
                        "draws one uniformly — mixed-tier traffic "
                        "exercises the batcher's tier-boundary cut; the "
                        "report breaks responses down per tier")
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--max-queue", type=int, default=4096)
    p.add_argument("--report", default="slo_report.json")
    p.add_argument("--seed", type=int, default=0)
    # ---- live observability plane (ISSUE 6) ----
    p.add_argument("--telemetry", choices=["off", "epoch"], default="off",
                   help="serving telemetry level: 'epoch' turns the live "
                        "plane fully on (span tracing + metrics.jsonl + "
                        "trace.json under --telemetry-dir) — the A/B leg "
                        "for the tracing-overhead measurement (PERF §13)")
    p.add_argument("--telemetry-dir", default="",
                   help="artifact dir for --telemetry epoch (default: a "
                        "temp dir next to the report)")
    p.add_argument("--no-scrape", action="store_true",
                   help="skip the mid-load /metrics scrape + the "
                        "scraped-vs-measured p99 agreement assertion")
    p.add_argument("--scrape-tolerance", type=float, default=0.5,
                   help="relative p99 disagreement tolerated between the "
                        "mid-load scrape and the loadgen's own "
                        "measurement (plus a 15 ms absolute floor)")
    p.add_argument("--profile-mid", action="store_true",
                   help="fire one bounded on-demand profile capture "
                        "mid-load (POST /profile on --http, the gated "
                        "ProfileCapture in-process) and assert it wrote "
                        "a non-empty artifact")
    return p


def make_synth_ckpt(ckpt_dir: str, seed: int = 0) -> None:
    """Commit a tiny trained-for-zero-epochs checkpoint (the serving
    fixture: real model config + normalizer + versioned-save protocol)."""
    import jax
    import numpy as np

    from cgnn_tpu.config import DataConfig, ModelConfig, build_model
    from cgnn_tpu.data.dataset import load_synthetic
    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.train import (
        CheckpointManager,
        Normalizer,
        create_train_state,
        make_optimizer,
    )

    model_cfg = ModelConfig(atom_fea_len=16, n_conv=2, h_fea_len=32,
                            dense_m=12)
    data_cfg = DataConfig(radius=6.0, max_num_nbr=12)
    graphs = load_synthetic(64, data_cfg.featurize_config(), seed=seed)
    nc, ec = capacities_for(graphs, 16, dense_m=12, snug=True)
    example = next(batch_iterator(graphs, 16, nc, ec, dense_m=12, in_cap=0,
                                  snug=True))
    model = build_model(model_cfg, data_cfg)
    state = create_train_state(
        model, example, make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(seed),
    )
    mgr = CheckpointManager(ckpt_dir)
    mgr.save(state, {"model": model_cfg.to_meta(), "data": data_cfg.to_meta(),
                     "task": "regression", "epoch": 0})
    mgr.close()
    print(f"committed synthetic checkpoint under {ckpt_dir} "
          f"({mgr.newest_committed()})")


def _perturbed_save(manager, template_state) -> None:
    """Commit a new version with visibly different params (the hot-swap
    fixture: predictions must change across the swap)."""
    import jax
    import numpy as np

    def nudge(x):
        a = np.asarray(x)
        if np.issubdtype(a.dtype, np.floating):
            return (a * 1.05 + 0.01).astype(a.dtype)
        return a

    new_state = template_state.replace(
        params=jax.tree_util.tree_map(nudge, template_state.params)
    )
    manager.save(new_state, dict(manager.read_meta("latest"), epoch=-1))
    manager.wait()


class _ClientStats:
    def __init__(self):
        self.lock = threading.Lock()
        self.latencies: list[float] = []
        self.versions: dict[str, int] = {}
        self.occupancies: list[float] = []
        self.submitted = 0
        self.answered = 0
        self.cached = 0
        self.rejected: dict[str, int] = {}
        self.dropped = 0
        self.errors: list[str] = []
        self.device_responses: dict[int, int] = {}
        # precision tier -> responses (the quantized-serving A/B record)
        self.precision_responses: dict[str, int] = {}
        # device_id -> param versions it answered with (the per-device
        # hot-swap consistency record)
        self.device_versions: dict[int, set] = {}
        # per-request tracing (ISSUE 6): every response must carry a
        # trace id, and co-batched requests must carry DISTINCT ids —
        # global uniqueness across the run covers both
        self.trace_ids: set = set()
        self.missing_trace = 0
        self.flush_ids: set = set()
        # wire form -> responses ('raw' | 'featurized'; ISSUE 11)
        self.wire_responses: dict[str, int] = {}
        # priority-class serving (ISSUE 19): per-class latencies (the
        # per-class p99 SLO asserts read these), per-class and
        # per-tenant answer counts, and answers that rode another
        # class's padding slack
        self.class_latencies: dict[str, list] = {}
        self.class_responses: dict[str, int] = {}
        self.tenant_responses: dict[str, int] = {}
        self.backfilled = 0


def _priority_plan(args) -> dict | None:
    """The mixed-priority load plan from the flag specs (ISSUE 19):
    rate-weighted class draw, per-class deadlines, tenant pool. None
    when --priority-mix is off."""
    from cgnn_tpu.serve.batcher import CLASSES, parse_kv_spec

    if not args.priority_mix:
        return None
    rates = parse_kv_spec(args.priority_mix)
    unknown = sorted(c for c in rates if c not in CLASSES)
    if unknown:
        raise SystemExit(
            f"--priority-mix: unknown classes {unknown} "
            f"(have: {list(CLASSES)})")
    rates = {c: float(r) for c, r in rates.items() if r > 0}
    if not rates:
        raise SystemExit("--priority-mix: no class with a rate > 0")
    total = sum(rates.values())
    classes = sorted(rates, key=lambda c: -rates[c])
    return {
        "rates": rates,
        "total": total,
        "classes": classes,
        "probs": [rates[c] / total for c in classes],
        "timeouts": parse_kv_spec(args.class_timeout_ms),
        "tenants": sorted(parse_kv_spec(args.tenants))
        if args.tenants else [],
    }


def _draw_priority(plan: dict, rng) -> tuple[str, str | None]:
    """One request's (class, tenant) draw: class rate-weighted,
    tenant uniform over the pool (None without --tenants)."""
    kl = plan["classes"][int(rng.choice(len(plan["classes"]),
                                        p=plan["probs"]))]
    tn = (plan["tenants"][int(rng.integers(len(plan["tenants"])))]
          if plan["tenants"] else None)
    return kl, tn


def _note_priority_answer(stats: _ClientStats, klass: str,
                          tenant: str | None, latency_ms: float,
                          backfilled: bool) -> None:
    """Record one answered request's class accounting. Caller holds
    ``stats.lock``."""
    stats.class_responses[klass] = (
        stats.class_responses.get(klass, 0) + 1)
    stats.class_latencies.setdefault(klass, []).append(
        float(latency_ms))
    if tenant:
        stats.tenant_responses[tenant] = (
            stats.tenant_responses.get(tenant, 0) + 1)
    if backfilled:
        stats.backfilled += 1


def _priority_report(stats: _ClientStats, plan: dict) -> dict:
    import numpy as np

    with stats.lock:
        by_cls = {c: list(v) for c, v in stats.class_latencies.items()}
        out = {
            "mix_rps": plan["rates"],
            "responses_by_class": dict(sorted(
                stats.class_responses.items())),
            "responses_by_tenant": dict(sorted(
                stats.tenant_responses.items())),
            "backfilled_responses": stats.backfilled,
        }
    out["latency_ms_by_class"] = {
        c: {
            "p50": float(np.percentile(np.asarray(lat), 50)),
            "p99": float(np.percentile(np.asarray(lat), 99)),
            "count": len(lat),
        }
        for c, lat in sorted(by_cls.items()) if lat
    }
    return out


def _measured_p99(stats: _ClientStats) -> float:
    import numpy as np

    with stats.lock:
        lat = list(stats.latencies)
    return float(np.percentile(np.asarray(lat), 99)) if lat else 0.0


def _scrape_check(text: str, scraped_p99: float,
                  measured_p99: float, tolerance: float) -> dict:
    """Validate one /metrics scrape: the exposition format must parse,
    the three metric families must be present, and the scraped rolling
    p99 must agree with the loadgen's own measurement within tolerance
    (relative, with a 15 ms absolute floor — the two windows and the
    two measurement points differ, so exact equality is not the bar)."""
    from cgnn_tpu.observe.export import parse_prometheus_text

    out = {"scraped_p99_ms": scraped_p99, "measured_p99_ms": measured_p99}
    try:
        fams = parse_prometheus_text(text)
        out["families"] = len(fams)
        out["parse_ok"] = True
    except ValueError as e:
        out["parse_ok"] = False
        out["parse_error"] = str(e)
        return out
    missing = [p for p in ("cgnn_serve_", "cgnn_device", "cgnn_pipeline_")
               if not any(f.startswith(p) for f in fams)]
    out["missing_families"] = missing
    tol = max(15.0, tolerance * max(scraped_p99, measured_p99))
    out["tolerance_ms"] = round(tol, 2)
    out["agree"] = abs(scraped_p99 - measured_p99) <= tol
    return out


def _fleet_hist_check(router, procs, stats) -> dict:
    """The metrics-truth pin (ISSUE 16), run AFTER the load quiesces so
    the replica histograms are static: scrape every replica's /metrics
    directly over real HTTP, merge the mergeable ``*_hist`` families
    locally, and compare against the router's own ``/metrics/fleet``
    scrape-and-merge — bucket counts AND sums must be bit-identical
    (integer counts add associatively; the exposition round-trips
    floats via repr). Then the merged latency histogram is checked
    against the clients' OWN measurements: its total count must cover
    every answered request (hedge stragglers and retried serves may add
    more, never fewer) and its median must agree with the measured p50
    within bucket resolution (x10^(1/6) ~ 1.47) plus a router/HTTP
    overhead margin."""
    import urllib.request

    import numpy as np

    from cgnn_tpu.observe.export import parse_prometheus_text
    from cgnn_tpu.observe.hist import (
        merge_snapshot_maps,
        quantile_from_snapshot,
    )

    out: dict = {"replicas_scraped": 0}
    fam_maps: dict[str, list] = {}
    for p in procs:
        try:
            with urllib.request.urlopen(p.base_url + "/metrics",
                                        timeout=10.0) as resp:
                text = resp.read().decode()
            fams = parse_prometheus_text(text)
        except Exception as e:  # noqa: BLE001 — reported as a failure
            out.setdefault("scrape_errors", []).append(repr(e))
            continue
        out["replicas_scraped"] += 1
        for name, fam in fams.items():
            if "histogram" in fam:
                fam_maps.setdefault(name, []).append(fam["histogram"])
    pooled = {name: merge_snapshot_maps(maps)
              for name, maps in fam_maps.items()}

    mismatches = []
    try:
        fleet_fams = parse_prometheus_text(router.fleet_metrics_text())
    except ValueError as e:
        fleet_fams = {}
        mismatches.append(f"/metrics/fleet did not parse: {e}")
    for name, merged in pooled.items():
        fhist = fleet_fams.get(name, {}).get("histogram")
        if fhist is None:
            mismatches.append(f"{name}: missing from /metrics/fleet")
            continue
        for key, snap in merged.items():
            fsnap = fhist.get(key)
            if fsnap is None:
                mismatches.append(f"{name}{{{key}}}: label set missing "
                                  f"from the fleet merge")
            elif (fsnap["counts"] != snap["counts"]
                  or fsnap["count"] != snap["count"]
                  or fsnap["sum"] != snap["sum"]):
                mismatches.append(
                    f"{name}{{{key}}}: fleet merge != pooled replica "
                    f"scrapes (count {fsnap['count']} vs "
                    f"{snap['count']}, sum {fsnap['sum']} vs "
                    f"{snap['sum']})")
    out["hist_families"] = sorted(pooled)
    out["merge_mismatches"] = mismatches
    out["merge_bitexact"] = not mismatches and bool(pooled)

    # the distribution truth is checked against the ROUTER's own fleet
    # latency histogram: it observes the same per-request total_ms the
    # clients record, so the count must match EXACTLY and the median
    # must agree within bucket resolution. The replica-side serve
    # histogram measures a different quantity (serve-core latency —
    # sub-ms on a cache hit) so it only gets a coverage bound.
    with stats.lock:
        lats = list(stats.latencies)
        answered = stats.answered
    fleet_lat = None
    try:
        router_fams = parse_prometheus_text(
            router.registry.prometheus_text())
        fleet_lat = router_fams.get(
            "cgnn_fleet_latency_ms_hist", {}).get("histogram", {}).get("")
    except ValueError as e:
        out["router_scrape_error"] = str(e)
    serve_snap = pooled.get("cgnn_serve_latency_ms_hist", {}).get("")
    if fleet_lat is not None and lats:
        hist_p50 = quantile_from_snapshot(fleet_lat, 0.5)
        measured_p50 = float(np.percentile(np.asarray(lats), 50))
        # one log-spaced bucket of slack (x10^(1/6) ~ 1.47, padded to
        # 1.6) plus a small absolute floor for sub-ms medians
        lo = hist_p50 / 1.6 - 5.0
        hi = hist_p50 * 1.6 + 5.0
        out["latency_truth"] = {
            "hist_count": fleet_lat["count"],
            "answered": answered,
            "count_exact": fleet_lat["count"] == answered,
            "hist_p50_ms": round(hist_p50, 3),
            "measured_p50_ms": round(measured_p50, 3),
            "p50_agree": lo <= measured_p50 <= hi,
            "replica_hist_count": (serve_snap or {}).get("count"),
            "count_covers_answered": (
                serve_snap is not None
                and serve_snap["count"] >= answered),
        }
    else:
        out["latency_truth"] = {
            "error": "no cgnn_fleet_latency_ms_hist on the router",
            "count_exact": False,
            "count_covers_answered": False,
            "p50_agree": False,
        }
    return out


def _slo_bundle_manifests(flightrec_dir: str) -> list:
    """Flight-recorder bundles whose MANIFEST names an SLO alert as the
    trigger reason — the ISSUE-16 page-as-evidence-bundle contract."""
    found = []
    try:
        names = sorted(os.listdir(flightrec_dir))
    except OSError:
        return found
    for d in names:
        mpath = os.path.join(flightrec_dir, d, "manifest.json")
        try:
            with open(mpath) as f:
                m = json.load(f)
        except (OSError, ValueError):
            continue
        if str(m.get("reason", "")).startswith("slo_burn_"):
            found.append({"bundle": d, "reason": m["reason"],
                          "detail": m.get("detail", "")})
    return found


def _run_inproc(args) -> dict:
    import tempfile

    import numpy as np

    from cgnn_tpu.observe import Telemetry
    from cgnn_tpu.serve.batcher import ServeRejection, parse_kv_spec
    from cgnn_tpu.serve.server import load_server

    if args.telemetry != "off":
        tdir = args.telemetry_dir or tempfile.mkdtemp(prefix="loadgen-obs-")
        telemetry = Telemetry(args.telemetry, tdir)
    else:
        telemetry = Telemetry.disabled()
    want_raw = args.wire in ("raw", "mixed")
    server, parts = load_server(
        args.ckpt_dir,
        batch_size=args.batch_size,
        rungs=args.rungs,
        telemetry=telemetry,
        max_queue=args.max_queue,
        max_wait_ms=args.max_wait_ms,
        compact=args.compact,
        # raw/mixed legs FORCE raw-wire serving (CPU CI would never
        # pick it under 'auto' — the host IS the device there)
        wire="raw" if want_raw else "auto",
        raw_precheck=not args.raw_overflow_probe,
        pack_workers=args.pack_workers,
        devices=args.devices,
        engine=args.engine,
        precision=args.precision,
        default_timeout_ms=args.timeout_ms,
        cache_size=0,  # the loadgen reuses structures; caching would
                       # let most requests skip the batcher under test
        watch=args.hot_swap,
        poll_interval_s=0.2,
        trace_ring=args.trace_ring,
        # priority-class serving knobs (ISSUE 19)
        class_max_wait_ms=(parse_kv_spec(args.class_wait_ms)
                           if args.class_wait_ms else None),
        backfill=not args.no_backfill,
        wfq_weights=(parse_kv_spec(args.tenants)
                     if args.tenants else None),
    )
    if args.profile_mid:
        server.enable_profiling(tempfile.mkdtemp(prefix="loadgen-prof-"))
    server.start()
    compiles_at_warm = server._jit_cache_size()

    from cgnn_tpu.data.dataset import load_synthetic
    from cgnn_tpu.data.rawbatch import raw_from_graph

    pool = load_synthetic(args.structures, parts["data_cfg"].
                          featurize_config(), seed=args.seed + 1,
                          keep_geometry=want_raw)
    pool = [g for g in pool if server.shape_set.admits(g)]
    raw_pool = []
    if want_raw:
        raw_pool = [r for r in (raw_from_graph(g) for g in pool)
                    if r is not None]

    stats = _ClientStats()
    stop = threading.Event()
    plan = _priority_plan(args)

    def client(ci: int):
        rng = np.random.default_rng(args.seed + ci)
        interval = 1.0 / args.rate if args.rate > 0 else 0.0
        if plan is not None:
            # open-loop mixed-priority load: the POOL sends plan.total
            # rps, so each client paces at clients/total
            interval = args.clients / plan["total"]
        tiers = [t.strip() for t in args.precision.split(",") if t.strip()]
        raw_share = {"featurized": 0.0, "mixed": 0.5, "raw": 1.0}[args.wire]
        while not stop.is_set():
            if raw_pool and rng.random() < raw_share:
                g = raw_pool[int(rng.integers(len(raw_pool)))]
            else:
                g = pool[int(rng.integers(len(pool)))]
            # uniform random tier per request: with more than one tier
            # this exercises the batcher's tier-boundary flush cut under
            # real concurrency (a random draw can starve a tier on very
            # short runs — the smoke leg's duration covers it)
            tier = tiers[int(rng.integers(len(tiers)))] if tiers else None
            kl = tn = None
            timeout_ms = args.timeout_ms
            if plan is not None:
                kl, tn = _draw_priority(plan, rng)
                timeout_ms = plan["timeouts"].get(kl, args.timeout_ms)
            t0 = time.monotonic()
            try:
                with stats.lock:
                    stats.submitted += 1
                fut = server.submit(g, timeout_ms=timeout_ms,
                                    precision=tier, klass=kl, tenant=tn)
                res = fut.result(timeout=timeout_ms / 1000.0 + 60.0)
            except ServeRejection as e:
                with stats.lock:
                    stats.rejected[e.reason] = (
                        stats.rejected.get(e.reason, 0) + 1
                    )
                continue
            except TimeoutError:
                with stats.lock:
                    stats.dropped += 1  # a hung future IS a drop
                continue
            except Exception as e:  # noqa: BLE001 — report, don't die
                with stats.lock:
                    stats.errors.append(repr(e))
                continue
            with stats.lock:
                stats.answered += 1
                stats.latencies.append(res.latency_ms)
                stats.versions[res.param_version] = (
                    stats.versions.get(res.param_version, 0) + 1
                )
                tier_got = getattr(res, "precision", "f32")
                stats.precision_responses[tier_got] = (
                    stats.precision_responses.get(tier_got, 0) + 1
                )
                di = getattr(res, "device_id", 0)
                stats.device_responses[di] = (
                    stats.device_responses.get(di, 0) + 1
                )
                stats.device_versions.setdefault(di, set()).add(
                    res.param_version
                )
                tid = getattr(res, "trace_id", "")
                if tid:
                    stats.trace_ids.add(tid)
                else:
                    stats.missing_trace += 1
                fid = getattr(res, "flush_id", "")
                if fid:
                    stats.flush_ids.add(fid)
                w = getattr(res, "wire", "featurized")
                stats.wire_responses[w] = stats.wire_responses.get(w, 0) + 1
                if plan is not None:
                    _note_priority_answer(
                        stats, getattr(res, "klass", "interactive"), tn,
                        res.latency_ms,
                        getattr(res, "backfilled", False))
                if res.cached:
                    stats.cached += 1
                else:
                    stats.occupancies.append(res.batch_occupancy)
            if interval:
                stop.wait(max(0.0, interval - (time.monotonic() - t0)))

    threads = [threading.Thread(target=client, args=(i,), daemon=True,
                                name=f"loadgen-client-{i}")
               for i in range(args.clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()

    # mid-load plane checks, each on its own timer thread so the load
    # keeps running underneath — that is the whole point of a LIVE plane
    scrape_result: dict = {}
    profile_result: dict = {}

    def mid_scrape():
        time.sleep(args.duration * 0.6)
        text = server.registry.prometheus_text()
        rolling = server.rolling_quantiles()
        scrape_result.update(
            at_s=round(time.monotonic() - t_start, 2),
            text_bytes=len(text),
            rolling=rolling,
            text=text,
            # the loadgen's own p99 over everything answered SO FAR —
            # the same window the 60 s rolling scrape covers; comparing
            # against the end-of-run p99 would mix in traffic the
            # scrape could not have seen yet
            measured_now_p99=_measured_p99(stats),
        )

    def mid_profile():
        time.sleep(args.duration * 0.4)
        from cgnn_tpu.observe import ProfileBusy

        try:
            profile_result.update(server.profiler.capture(0.5), ok=True)
        except ProfileBusy as e:
            profile_result.update(ok=False, error=str(e))
        except Exception as e:  # noqa: BLE001 — reported as a failure
            profile_result.update(ok=False, error=repr(e))

    checkers = []
    if not args.no_scrape:
        checkers.append(threading.Thread(target=mid_scrape, daemon=True,
                                         name="loadgen-scrape"))
    if args.profile_mid:
        checkers.append(threading.Thread(target=mid_profile, daemon=True,
                                         name="loadgen-profile"))
    for t in checkers:
        t.start()

    # trace-id probe: a request submitted with an explicit id must echo
    # it back on its result (the X-Request-Id contract, in-process form)
    probe_trace = None
    if pool:
        try:
            probe = server.submit(pool[0], timeout_ms=args.timeout_ms,
                                  trace_id="loadgen-probe-1")
            probe_trace = probe.result(
                timeout=args.timeout_ms / 1000.0 + 60.0).trace_id
        except Exception as e:  # noqa: BLE001 — reported as a failure
            probe_trace = f"ERROR: {e!r}"

    # raw-wire probes (ISSUE 11), fired alongside the load:
    # - parity: ONE structure submitted both raw and featurized must
    #   agree to f32 roundoff (the two wire forms run different warmed
    #   programs — the in-program search vs the host featurizer);
    # - overflow (with --raw-overflow-probe): a tiny cell needing more
    #   periodic images than the calibrated caps, admitted past the
    #   disabled pre-check — the IN-PROGRAM flag must catch it and the
    #   featurized fallback answer it (wire='featurized', counter > 0).
    raw_probe: dict = {}
    if want_raw and raw_pool:
        try:
            pg, pr = next(
                (g, r) for g, r in ((g, raw_from_graph(g)) for g in pool)
                if r is not None and server.shape_set.admits_raw(r)
            )
            r_raw = server.submit(pr, timeout_ms=args.timeout_ms)
            r_feat = server.submit(pg, timeout_ms=args.timeout_ms)
            a = r_raw.result(args.timeout_ms / 1000.0 + 60.0)
            b = r_feat.result(args.timeout_ms / 1000.0 + 60.0)
            diff = float(np.abs(a.prediction - b.prediction).max())
            raw_probe["parity"] = {
                "wire_a": a.wire, "wire_b": b.wire,
                "max_abs_diff": diff,
                "ok": a.wire == "raw" and diff < 1e-3,
            }
        except Exception as e:  # noqa: BLE001 — reported as a failure
            raw_probe["parity"] = {"ok": False, "error": repr(e)}
    if args.raw_overflow_probe and want_raw:
        from cgnn_tpu.data.rawbatch import RawStructure

        tiny = RawStructure(
            np.array([[0.2, 0.2, 0.2], [0.7, 0.6, 0.5]]),
            np.eye(3) * 1.8, np.array([6, 8], np.int32),
            cif_id="overflow-probe",
        )
        try:
            res = server.predict(tiny, timeout_ms=args.timeout_ms)
            raw_probe["overflow"] = {
                "wire": res.wire,
                "ok": res.wire == "featurized",
            }
        except Exception as e:  # noqa: BLE001 — reported as a failure
            raw_probe["overflow"] = {"ok": False, "error": repr(e)}

    swapped_to = None
    if args.hot_swap:
        time.sleep(args.duration / 2)
        state, _ = server.param_store.get()
        _perturbed_save(parts["manager"], state)
        # the watcher polls at 0.2 s; give it a moment inside the window
        deadline = time.monotonic() + max(5.0, args.duration / 4)
        while time.monotonic() < deadline:
            if server._watcher is not None and server._watcher.swaps:
                swapped_to = server.param_store.version
                break
            time.sleep(0.05)

    while time.monotonic() - t_start < args.duration:
        time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(timeout=args.timeout_ms / 1000.0 + 90.0)
    for t in checkers:
        t.join(timeout=30.0)
    wall = time.monotonic() - t_start
    server.drain(timeout_s=60.0)
    compiles_at_end = server._jit_cache_size()
    if telemetry.enabled:
        telemetry.close()  # exports trace.json with the request spans

    lat = np.asarray(stats.latencies) if stats.latencies else np.zeros(1)
    report = {
        "mode": "inproc",
        "clients": args.clients,
        "duration_s": round(wall, 2),
        "submitted": stats.submitted,
        "answered": stats.answered,
        "rejected": stats.rejected,
        "dropped": stats.dropped,
        "client_errors": stats.errors[:10],
        "throughput_rps": round(stats.answered / wall, 1),
        "latency_ms": {
            "p50": float(np.percentile(lat, 50)),
            "p95": float(np.percentile(lat, 95)),
            "p99": float(np.percentile(lat, 99)),
            "mean": float(lat.mean()),
        },
        "batch_occupancy_mean": (
            float(np.mean(stats.occupancies)) if stats.occupancies else 0.0
        ),
        "param_versions": stats.versions,
        "precision": {
            "requested": args.precision,
            "responses_by_tier": dict(sorted(
                stats.precision_responses.items())),
        },
        "wire": {
            "requested": args.wire,
            "responses_by_wire": dict(sorted(
                stats.wire_responses.items())),
            "raw_pool": len(raw_pool),
            "probes": raw_probe,
        },
        "devices": {
            "requested": str(args.devices),
            "engine": server.engine,
            "count": len(server.device_set),
            "responses_by_device": {
                str(k): v
                for k, v in sorted(stats.device_responses.items())
            },
            "versions_by_device": {
                str(k): sorted(v)
                for k, v in sorted(stats.device_versions.items())
            },
        },
        "hot_swap": {
            "requested": bool(args.hot_swap),
            "swapped_to": swapped_to,
            "watcher_swaps": (server._watcher.swaps
                              if server._watcher else 0),
            "watcher_skips": (server._watcher.skips
                              if server._watcher else 0),
        },
        "compiles": {
            "at_warm": compiles_at_warm,
            "at_end": compiles_at_end,
            "after_warm": (compiles_at_end or 0) - (compiles_at_warm or 0),
        },
        "tracing": {
            "unique_trace_ids": len(stats.trace_ids),
            "missing_trace_ids": stats.missing_trace,
            "flushes_observed": len(stats.flush_ids),
            "probe_trace_id": probe_trace,
            "telemetry": args.telemetry,
            "trace_json": (os.path.join(telemetry.log_dir, "trace.json")
                           if telemetry.enabled else None),
        },
        "server_stats": server.stats(),
    }
    if plan is not None:
        report["priority"] = {
            **_priority_report(stats, plan),
            # the server's own backfill accounting (numerator over the
            # slack the higher-class flushes offered)
            "padding_fill_share": report["server_stats"]["priority"][
                "padding_fill_share"],
            "backfill_enabled": report["server_stats"]["priority"][
                "backfill"],
        }
    if scrape_result:
        report["metrics_scrape"] = {
            "at_s": scrape_result["at_s"],
            "text_bytes": scrape_result["text_bytes"],
            "final_measured_p99_ms": _measured_p99(stats),
            **_scrape_check(
                scrape_result["text"],
                scrape_result.get("rolling", {}).get("p99", 0.0),
                scrape_result.get("measured_now_p99", 0.0),
                args.scrape_tolerance,
            ),
        }
    if profile_result:
        report["profile"] = profile_result
    return report


def _commit_new_version(ckpt_dir: str, seed: int) -> str:
    """Commit a fresh param version into the fleet's shared checkpoint
    directory (the rolling-promotion fixture): same configs as the
    resident checkpoint, different init — predictions visibly change,
    every replica's watcher rolls it in. Returns the new save name."""
    import jax
    import numpy as np

    from cgnn_tpu.config import DataConfig, ModelConfig, build_model
    from cgnn_tpu.data.dataset import load_synthetic
    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.train import (
        CheckpointManager,
        Normalizer,
        create_train_state,
        make_optimizer,
    )

    mgr = CheckpointManager(ckpt_dir)
    meta = mgr.read_meta("latest")
    model_cfg = ModelConfig.from_meta(meta["model"])
    data_cfg = DataConfig.from_meta(meta["data"])
    graphs = load_synthetic(64, data_cfg.featurize_config(), seed=seed)
    nc, ec = capacities_for(graphs, 16, dense_m=model_cfg.dense_m,
                            snug=True)
    example = next(batch_iterator(graphs, 16, nc, ec,
                                  dense_m=model_cfg.dense_m, in_cap=0,
                                  snug=True))
    model = build_model(model_cfg, data_cfg, meta.get("task", "regression"))
    state = create_train_state(
        model, example, make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(seed),
    )
    mgr.save(state, dict(meta, epoch=int(meta.get("epoch", 0)) + 1))
    mgr.wait()
    name = mgr.newest_committed()
    mgr.close()
    return name


def _run_fleet(args) -> dict:
    """The fleet chaos harness (ISSUE 14): N real serve.py replica
    processes behind the in-process FleetRouter, open-loop load driven
    THROUGH the router while the chaos legs kill -9 / restart replicas
    and roll a checkpoint promotion underneath it.

    The invariants hard-asserted here (main() exits non-zero):

    - ZERO lost accepted requests: every dispatch resolves to exactly
      one typed outcome — an answer or an explicit rejection — even
      while a replica dies mid-request (retried onto survivors);
    - EXACTLY ONE answer per request: distinct trace ids == answered
      and the router's duplicate-answer counter stays 0, under retries
      AND hedges (the idempotency key is the trace id every attempt
      shares);
    - a killed replica is probed back in after restart and answers
      again; a rolling promotion serves BOTH versions mid-roll and
      converges version-consistent fleet-wide."""
    import numpy as np

    from cgnn_tpu.config import DataConfig
    from cgnn_tpu.fleet.replica import ReplicaState
    from cgnn_tpu.fleet.router import FleetRouter
    from cgnn_tpu.fleet.spawn import ReplicaProcess
    from cgnn_tpu.train import CheckpointManager

    n = args.fleet
    log_dir = args.fleet_log_dir or (
        os.path.join(os.path.dirname(os.path.abspath(args.report)) or ".",
                     "fleet-logs"))
    os.makedirs(log_dir, exist_ok=True)
    serve_args = [
        "--calibrate", "64",
        "--batch-size", str(args.batch_size),
        "--rungs", str(args.rungs),
        "--max-queue", str(args.max_queue),
        "--max-wait-ms", str(args.max_wait_ms),
        "--poll-interval", "0.5",
        "--drain-timeout", "30",
    ]
    # priority-class serving knobs (ISSUE 19) ride to every replica
    if args.class_wait_ms:
        serve_args += ["--class-wait-ms", args.class_wait_ms]
    if args.no_backfill:
        serve_args += ["--no-backfill"]
    if args.tenants:
        serve_args += ["--wfq-weights", args.tenants]
    if args.autoscale or args.remediate:
        # drain with the listener up, then linger past a health-probe
        # round (0.5 s here) so the router OBSERVES the draining flag
        # before the process exits — what classifies the disappearance
        # as a scale event instead of an incident
        serve_args += ["--drain-linger", "1.5"]
    if args.continual:
        # candidates must NOT auto-roll into replicas: every watcher
        # holds at its boot version until the canary gate's promotion
        # broadcast raises the reload gate (serve/reload.py)
        serve_args += ["--reload-gated"]
    procs = []
    for i in range(n):
        env = dict(os.environ)
        if args.replica_faults and i == args.faulty_replica % n:
            env["CGNN_TPU_FAULTS"] = args.replica_faults
        procs.append(ReplicaProcess(
            i, args.ckpt_dir, args.fleet_base_port + i,
            log_path=os.path.join(log_dir, f"replica-{i}.log"),
            serve_args=serve_args, env=env,
        ).start())
    not_ready = [p.rid for p in procs if not p.wait_ready(300.0)]
    if not_ready:
        for p in procs:
            p.terminate(timeout_s=5.0)
        raise RuntimeError(f"replicas {not_ready} never became ready "
                           f"(logs under {log_dir})")

    replicas = [ReplicaState(p.rid, p.base_url,
                             breaker_k=args.breaker_k,
                             breaker_cooldown_s=args.breaker_cooldown)
                for p in procs]
    slo_kw: dict = {}
    if args.slo_report:
        # second-scale burn-rate rules (ISSUE 16) so the injected 5xx
        # burst walks the full inactive -> pending -> firing ->
        # resolved arc inside one smoke run: fire when BOTH the 2 s and
        # 8 s windows burn >2x the 99.9% budget for 0.5 s; resolve
        # within ~8 s of the burst ending (the router's tsdb heartbeat
        # keeps evaluating with zero traffic)
        from cgnn_tpu.observe.slo import BurnRateRule, SLOObjective

        slo_kw = {
            "slo_objectives": (SLOObjective(
                "fleet_availability", target=0.999, window_s=60.0),),
            "slo_rules": (BurnRateRule(fast_s=2.0, slow_s=8.0,
                                       factor=2.0, for_s=0.5),),
        }
    router = FleetRouter(
        replicas,
        max_attempts=args.retries + 1,
        hedge_ms=args.hedge_ms,
        default_timeout_ms=args.timeout_ms,
        health_interval_s=0.5,
        trace_ring=args.trace_ring,
        **slo_kw,
    ).start()

    # the incident flight recorder under test (ISSUE 15): breaker trips
    # (the kill -9 leg ejects the victim) and 5xx bursts dump a bundle
    # holding the JOINED fleet trace + every process's request ring —
    # asserted below when --expect-trace-join
    from cgnn_tpu.observe import FlightRecorder

    flightrec_dir = os.path.join(
        os.path.dirname(os.path.abspath(args.report)) or ".",
        "flightrec")
    recorder = None
    if args.trace_ring:
        recorder = FlightRecorder(
            flightrec_dir, role="router", name="loadgen-router",
            registry=router.registry, tracer=router.tracer,
            peers=router.replica_trace_urls(),
            manifest={"ckpt_dir": args.ckpt_dir, "replicas": n},
            log_fn=print,
            # short quiet window: the chaos legs WANT each distinct
            # trigger captured — a kill's breaker_trip must not
            # rate-limit away the replica_unreachable bundle one probe
            # round (0.5 s) later, which is the one whose joined trace
            # provably holds the completed retries
            min_interval_s=0.25,
        )
        router.attach_flight_recorder(recorder)

    # ---- the label journal + /label wire surface (ISSUE 18) ----
    journal = None
    journal_path = ""
    label_httpd = None
    label_url = ""
    label_feedback = args.label_feedback
    if args.continual and label_feedback <= 0.0:
        label_feedback = 1.0  # the loop trains on labels; feed them all
    if label_feedback > 0.0:
        from cgnn_tpu.continual import LabelJournal
        from cgnn_tpu.fleet.http import make_fleet_http_server

        journal_path = os.path.join(
            os.path.dirname(os.path.abspath(args.report)) or ".",
            "labels.jsonl")
        for stale in (journal_path, journal_path + ".1"):
            if os.path.exists(stale):
                os.remove(stale)
        # durable only when a trainer tails it cross-process
        journal = LabelJournal(journal_path if args.continual else None,
                               capacity=65536)
        router.attach_journal(journal)
        # labels arrive over the SAME wire surface operators use:
        # POST /label against the router's HTTP front-end
        label_port = args.fleet_base_port + 99
        label_httpd = make_fleet_http_server(router, port=label_port)
        threading.Thread(target=label_httpd.serve_forever, daemon=True,
                         name="loadgen-fleet-http").start()
        label_url = f"http://127.0.0.1:{label_port}/label"

    # ---- the self-driving layer (ISSUE 17) ----
    autoscaler = None
    remediator = None
    asc_t0_mono = 0.0
    if args.autoscale or args.remediate:
        from cgnn_tpu.fleet.autoscale import AutoscalePolicy, Autoscaler
        from cgnn_tpu.fleet.remediate import (
            RemediationPolicy,
            Remediator,
        )

        def _proc_factory(rid: int):
            return ReplicaProcess(
                rid, args.ckpt_dir, args.fleet_base_port + rid,
                log_path=os.path.join(log_dir, f"replica-{rid}.log"),
                serve_args=serve_args)

        def _state_factory(rid: int, base_url: str):
            return ReplicaState(rid, base_url,
                                breaker_k=args.breaker_k,
                                breaker_cooldown_s=args.breaker_cooldown)

        # smoke-scale policy: second-scale cooldowns/sustain so the
        # whole grow-then-shrink arc fits inside one short leg
        asc_policy = AutoscalePolicy(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            up_queue_per_replica=2.0,
            down_queue_per_replica=0.4,
            cooldown_up_s=2.0, cooldown_down_s=4.0, down_sustain_s=3.0,
            warm_target=args.warm_pool)
        asc_t0_mono = time.monotonic()
        autoscaler = Autoscaler(
            router, asc_policy, _proc_factory, _state_factory,
            procs={p.rid: p for p in procs}, next_rid=n,
            poll_interval_s=0.5, drain_timeout_s=30.0)
        router.autoscaler = autoscaler
        if args.warm_pool > 0:
            warmed = autoscaler.prewarm()
            print(f"loadgen: prewarmed {warmed} spare replica(s) "
                  f"(pool {autoscaler.stats()['warm_pool']})")
        if args.autoscale:
            autoscaler.start()
        if args.remediate:
            if recorder is None:
                raise RuntimeError("--remediate needs the flight "
                                   "recorder (--trace-ring > 0)")
            remediator = Remediator(
                router, autoscaler,
                RemediationPolicy(min_interval_s=2.0),
                out_dir=os.path.dirname(os.path.abspath(args.report))
                or ".",
                # a wedged victim cannot drain; kill9 past this bound
                drain_timeout_s=8.0,
            ).attach(recorder)
            router.remediator = remediator

    from cgnn_tpu.data.dataset import load_synthetic

    meta = CheckpointManager(args.ckpt_dir).read_meta("latest")
    data_cfg = DataConfig.from_meta(meta["data"])
    pool = load_synthetic(min(args.structures, 64),
                          data_cfg.featurize_config(), seed=args.seed + 1)
    bodies = [{"graph": {
        "atom_fea": g.atom_fea.tolist(),
        "edge_fea": g.edge_fea.tolist(),
        "centers": g.centers.tolist(),
        "neighbors": g.neighbors.tolist(),
        "id": g.cif_id,
    }} for g in pool]
    # ground truth per body, for the late-label feed: the synthetic
    # pool's real targets, so the continual trainer fine-tunes on a
    # signal that actually exists
    truths = [float(np.asarray(g.target).reshape(-1)[0]) for g in pool]

    # Zipf keyset (ISSUE 20): body 0 is the hottest key. Precomputed
    # once; every client thread draws from the same distribution.
    zipf_p = None
    if args.zipf > 0:
        zipf_p = np.array([1.0 / (i + 1) ** args.zipf
                           for i in range(len(bodies))])
        zipf_p /= zipf_p.sum()

    stats = _ClientStats()
    stop = threading.Event()
    # per-replica answered counts + resilience meta, as the CLIENTS saw
    # them (the router's own stats ride the report separately)
    fleet_counts = {"attempts_hist": {}, "hedged_answers": 0,
                    "retried_answers": 0}
    # (due_time, trace_id, truth) entries awaiting their POST /label
    from collections import deque

    label_lock = threading.Lock()
    label_q: deque = deque()
    label_log: dict = {"sent": 0, "joined": 0, "already": 0,
                       "unmatched": 0, "double_posts": 0,
                       "resend_not_already": 0, "post_errors": []}

    # open-loop rate ramp (ISSUE 17): fleet-total rps as a function of
    # elapsed fraction — hold LOW, climb to PEAK by mid-duration, hold,
    # then drop to a calm tail (the autoscaler's scale-down window)
    ramp = None
    if args.ramp:
        _lo, _peak = (float(x) for x in args.ramp.split(":", 1))
        ramp = (_lo, _peak)

    def _ramp_rate(frac: float) -> float:
        lo, peak = ramp
        if frac < 0.1:
            return lo
        if frac < 0.45:
            return lo + (peak - lo) * (frac - 0.1) / 0.35
        if frac < 0.6:
            return peak
        return max(lo * 0.5, 0.5)

    plan = _priority_plan(args)

    def client(ci: int):
        import numpy as _np

        rng = _np.random.default_rng(args.seed + ci)
        while not stop.is_set():
            t_pace = None
            if ramp is not None:
                frac = (time.monotonic() - t_start) / max(args.duration,
                                                          1e-9)
                rate = _ramp_rate(min(frac, 1.0))
                t_pace = time.monotonic() + args.clients / max(rate, 0.1)
            elif plan is not None:
                # open-loop mixed-priority load at plan.total rps
                t_pace = (time.monotonic()
                          + args.clients / max(plan["total"], 0.1))
            if zipf_p is not None:
                bi = int(rng.choice(len(bodies), p=zipf_p))
            else:
                bi = int(rng.integers(len(bodies)))
            body = bodies[bi]
            kl = tn = None
            timeout_ms = args.timeout_ms
            if plan is not None:
                kl, tn = _draw_priority(plan, rng)
                timeout_ms = plan["timeouts"].get(kl, args.timeout_ms)
                body = dict(body, **{"class": kl})
                if tn:
                    body["tenant"] = tn
            with stats.lock:
                stats.submitted += 1
            try:
                status, payload, meta_d = router.dispatch(
                    dict(body), timeout_ms=timeout_ms)
            except Exception as e:  # noqa: BLE001 — report, don't die
                with stats.lock:
                    stats.errors.append(repr(e))
                if t_pace is not None:
                    stop.wait(max(0.0, t_pace - time.monotonic()))
                continue
            if t_pace is not None:
                stop.wait(max(0.0, t_pace - time.monotonic()))
            with stats.lock:
                if status == 200:
                    stats.answered += 1
                    stats.latencies.append(float(meta_d["latency_ms"]))
                    v = payload.get("param_version", "?")
                    stats.versions[v] = stats.versions.get(v, 0) + 1
                    rid = meta_d["replica"]
                    stats.device_responses[rid] = (
                        stats.device_responses.get(rid, 0) + 1)
                    stats.device_versions.setdefault(rid, set()).add(v)
                    tid = meta_d["trace_id"]
                    if tid:
                        stats.trace_ids.add(tid)
                    else:
                        stats.missing_trace += 1
                    a = meta_d["attempts"]
                    fleet_counts["attempts_hist"][a] = (
                        fleet_counts["attempts_hist"].get(a, 0) + 1)
                    if meta_d["hedges"]:
                        fleet_counts["hedged_answers"] += 1
                    if meta_d["retries"]:
                        fleet_counts["retried_answers"] += 1
                    if plan is not None:
                        _note_priority_answer(
                            stats,
                            str(payload.get("class") or kl
                                or "interactive"),
                            tn, float(meta_d["latency_ms"]),
                            bool(payload.get("backfilled")))
                else:
                    reason = (payload or {}).get("reason", str(status))
                    stats.rejected[reason] = (
                        stats.rejected.get(reason, 0) + 1)
            if (journal is not None and status == 200
                    and rng.random() < label_feedback):
                # ground truth "arrives" label_delay_ms later — the
                # labeler thread POSTs it to /label then
                with label_lock:
                    label_q.append((
                        time.monotonic() + args.label_delay_ms / 1e3,
                        meta_d["trace_id"], truths[bi]))

    threads = [threading.Thread(target=client, args=(i,), daemon=True,
                                name=f"loadgen-fleet-client-{i}")
               for i in range(args.clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()

    # ---- the late-label feed (ISSUE 18) ----
    labeler_threads: list = []
    if journal is not None:
        import urllib.request
        from urllib.error import HTTPError, URLError

        def _post_label(tid: str, y: float) -> str:
            data = json.dumps({"trace_id": tid, "label": y},
                              allow_nan=False).encode()
            req = urllib.request.Request(
                label_url, data=data, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=10.0) as resp:
                    return json.loads(resp.read()).get("status", "?")
            except HTTPError as e:
                # 404 still carries {"status": "unmatched"}
                try:
                    return json.loads(e.read()).get("status", "?")
                except ValueError:
                    return f"http_{e.code}"

        # a POOL of labelers: each POST costs a fresh TCP connection
        # (~ms), so a single thread falls minutes behind a busy fleet
        # and labels would join long after their version's canary
        # window — staling the gate's live baseline
        def labeler():
            while True:
                entry = None
                with label_lock:
                    # once the run is stopping, flush without the delay
                    # so the exactly-once ledger closes complete
                    if label_q and (label_q[0][0] <= time.monotonic()
                                    or stop.is_set()):
                        entry = label_q.popleft()
                    drained = not label_q
                if entry is None:
                    if stop.is_set() and drained:
                        return
                    time.sleep(0.005)
                    continue
                _due, tid, y = entry
                try:
                    status = _post_label(tid, y)
                except (URLError, OSError) as e:
                    with label_lock:
                        label_log["post_errors"].append(repr(e))
                    continue
                with label_lock:
                    label_log["sent"] += 1
                    label_log[status] = label_log.get(status, 0) + 1
                    resend = label_log["sent"] % 7 == 0
                    if resend:
                        label_log["double_posts"] += 1
                if not resend:
                    continue
                # deliberately retransmit this label: exactly-once
                # means the journal answers 'already' and the stored
                # value stays untouched
                try:
                    again = _post_label(tid, y)
                except (URLError, OSError) as e:
                    with label_lock:
                        label_log["post_errors"].append(repr(e))
                    continue
                if again != "already":
                    with label_lock:
                        label_log["resend_not_already"] += 1

        labeler_threads = [
            threading.Thread(target=labeler, daemon=True,
                             name=f"loadgen-fleet-labeler-{i}")
            for i in range(6)]
        for t in labeler_threads:
            t.start()

    # ---- the closed loop (ISSUE 18): trainer + canary gate ----
    continual_done = threading.Event()
    continual_log: dict = {}
    canary_ctl = None
    canary_mgr = None
    cont_proc = None
    cont_log_path = ""
    if args.continual:
        from cgnn_tpu.continual import (
            CanaryController,
            CanaryGate,
            GateConfig,
        )

        canary_mgr = CheckpointManager(args.ckpt_dir)
        base_version = canary_mgr.newest_committed()
        # smoke-scale gate: loose MAE ratios (tiny fine-tunes on the
        # synthetic pool are noisy, while the injected round-2 label
        # corruption blows far past 4x) and short windows so both
        # verdicts land inside one leg
        canary_ctl = CanaryController(
            gate=CanaryGate(GateConfig(
                min_samples=20, min_baseline=20,
                max_mae_ratio=2.0, rollback_mae_ratio=4.0,
                p99_budget_ms=float(args.timeout_ms),
                min_window_s=1.0, max_window_s=120.0)),
            journal=journal, fleet=router,
            newest_fn=canary_mgr.newest_committed,
            flightrec=recorder,
            tick_interval_s=0.25,
            shadow_timeout_s=args.timeout_ms / 1e3,
            log_fn=print,
        )
        router.attach_canary(canary_ctl)
        canary_ctl.start()
        cont_log_path = os.path.join(log_dir, "continual.log")
        cont_env = dict(os.environ)
        cont_env["JAX_PLATFORMS"] = "cpu"
        # round 2 trains on deliberately corrupted labels: the
        # regressing candidate the canary gate MUST refuse
        cont_env["CGNN_TPU_FAULTS"] = "label_noise=2:10.0"
        with open(cont_log_path, "w") as cont_log_fh:
            cont_proc = subprocess.Popen(
                [sys.executable,
                 os.path.join(os.path.dirname(os.path.dirname(
                     os.path.abspath(__file__))), "continual.py"),
                 args.ckpt_dir, "--journal", journal_path,
                 "--min-new-labels", "48",
                 # round 2 must wait for candidate 1's verdict: the
                 # controller evaluates ONE candidate at a time and
                 # only ever picks the newest commit
                 "--min-interval", "45",
                 "--epochs-per-round", "2",
                 "--batch-size", "16",
                 "--max-rounds", "2",
                 "--poll-interval", "0.5",
                 "--device", "cpu",
                 "--seed", str(args.seed)],
                stdout=cont_log_fh, stderr=subprocess.STDOUT,
                env=cont_env)

        def continual_watch():
            commits: list = []
            deadline = time.monotonic() + 600.0
            try:
                while time.monotonic() < deadline:
                    newest = canary_mgr.newest_committed()
                    if (newest and newest != base_version
                            and newest not in commits):
                        commits.append(newest)
                        continual_log.setdefault(
                            "commit_times_s", []).append(
                            round(time.monotonic() - t_start, 2))
                    ev = canary_ctl.stats()["events"]
                    promoted = [e for e in ev
                                if e["kind"] == "promoted"]
                    rolled = [e for e in ev
                              if e["kind"] == "rolled_back"]
                    returned = [e for e in ev
                                if e["kind"] == "canary_returned"]
                    if promoted and "promoted" not in continual_log:
                        continual_log["promoted"] = (
                            promoted[0]["version"])
                        continual_log["promoted_at_s"] = round(
                            time.monotonic() - t_start, 2)
                    if rolled and returned and len(commits) >= 2:
                        continual_log["rolled_back"] = (
                            rolled[0]["version"])
                        continual_log["rollback_reason"] = (
                            rolled[0].get("reason", ""))
                        break
                    time.sleep(0.5)
                # promotion must CONVERGE: every routed replica's
                # gated watcher rolls onto the promoted version
                if "promoted" in continual_log:
                    pv = continual_log["promoted"]
                    conv_deadline = time.monotonic() + 90.0
                    consistent = False
                    while time.monotonic() < conv_deadline:
                        if set(router.versions().values()) == {pv}:
                            consistent = True
                            break
                        time.sleep(0.25)
                    continual_log["promotion_consistent"] = consistent
            finally:
                continual_log["commits"] = commits
                continual_done.set()

        threading.Thread(target=continual_watch, daemon=True,
                         name="loadgen-continual-watch").start()
    else:
        continual_done.set()

    # ---- the chaos timeline, alongside the load ----
    chaos_done = threading.Event()
    promote_done = threading.Event()
    chaos_log: dict = {}
    victim = args.kill_replica % n

    def _fleet_cache_counts() -> dict:
        # sums the replicas' OWN /stats cache counters over HTTP — they
        # are separate processes, so the router's view is not enough; a
        # kill9'd replica is simply skipped
        from cgnn_tpu.fleet.replica import http_get_json
        tot = {"requests": 0, "cache_hits": 0, "cache_coalesced": 0,
               "cache_dup_misses": 0, "cache_fills": 0}
        for p in procs:
            try:
                _, s = http_get_json(p.base_url + "/stats",
                                     timeout_s=5.0)
            except Exception:  # noqa: BLE001 — dead replica mid-chaos
                continue
            c = s.get("counts", {})
            for k in tot:
                tot[k] += int(c.get(k, 0))
        return tot

    # owner-kill leg (ISSUE 20): the victim is the ring owner of the
    # hottest key — computed BEFORE the load starts, since the ring is
    # deterministic. rid == proc index for the initial fleet.
    cachepart_log: dict = {}
    hot_key = None
    if args.kill_owner and router.cache_ring is not None:
        from cgnn_tpu.fleet.router import edge_fingerprint

        hot_key = edge_fingerprint(bodies[0])
        owner0 = router.cache_ring.owner(hot_key)
        if owner0 is not None:
            victim = int(owner0) % n
        cachepart_log["hot_fingerprint"] = hot_key
        cachepart_log["owner_before"] = owner0

    def chaos():
        try:
            if args.kill_at > 0:
                stop.wait(args.duration * args.kill_at)
                procs[victim].kill9()
                chaos_log["killed_at_s"] = round(
                    time.monotonic() - t_start, 2)
                if hot_key is not None:
                    # the prober needs a round to see the corpse; then
                    # the health-aware walk must re-own the victim's
                    # arcs to a deterministic ring successor
                    deadline_o = time.monotonic() + 15.0
                    during = None
                    while time.monotonic() < deadline_o:
                        alive = {r.rid for r in router.replicas
                                 if r.pickable()}
                        during = router.cache_ring.owner(hot_key,
                                                         alive=alive)
                        if during is not None and during != victim:
                            break
                        time.sleep(0.25)
                    cachepart_log["owner_during_kill"] = during
            if args.restart_at > 0:
                stop.wait(max(0.0, args.duration * args.restart_at
                              - (time.monotonic() - t_start)))
                procs[victim].restart()
                ready = procs[victim].wait_ready(240.0)
                chaos_log["restarted_at_s"] = round(
                    time.monotonic() - t_start, 2)
                chaos_log["restart_ready"] = ready
                # snapshot the victim's answered count the moment it is
                # back: "serves again" = the count GROWS past this
                chaos_log["victim_answered_at_restart"] = (
                    replicas[victim].counts["answered"])
                if hot_key is not None and ready:
                    # re-ownership must REVERT once the victim probes
                    # healthy again (remove + add restores the mapping
                    # bit-exactly — pinned by tests/test_cache_ring.py)
                    deadline_o = time.monotonic() + 30.0
                    after_o = None
                    while time.monotonic() < deadline_o:
                        alive = {r.rid for r in router.replicas
                                 if r.pickable()}
                        after_o = router.cache_ring.owner(hot_key,
                                                          alive=alive)
                        if after_o == cachepart_log.get("owner_before"):
                            break
                        time.sleep(0.25)
                    cachepart_log["owner_after_restart"] = after_o
                    # recovery is judged on the POST-restart window
                    # alone: snapshot fleet cache counters now, diff at
                    # the end
                    cachepart_log["counters_at_restart"] = (
                        _fleet_cache_counts())
        finally:
            chaos_done.set()

    def promote():
        try:
            if args.promote_at > 0:
                stop.wait(args.duration * args.promote_at)
                new_version = _commit_new_version(args.ckpt_dir,
                                                  seed=args.seed + 777)
                chaos_log["promoted_to"] = new_version
                chaos_log["promoted_at_s"] = round(
                    time.monotonic() - t_start, 2)
                # rolling promotion: every replica's own watcher polls
                # the shared dir — wait (bounded) until the router's
                # health view reports the new version fleet-wide
                deadline = time.monotonic() + 60.0
                consistent = False
                while time.monotonic() < deadline:
                    vs = set(router.versions().values())
                    if vs == {new_version}:
                        consistent = True
                        break
                    time.sleep(0.25)
                chaos_log["promotion_consistent"] = consistent
                chaos_log["final_versions"] = {
                    str(k): v for k, v in router.versions().items()}
        except Exception as e:  # noqa: BLE001 — reported as a failure
            chaos_log["promotion_error"] = repr(e)
        finally:
            promote_done.set()

    side = [threading.Thread(target=chaos, daemon=True,
                             name="loadgen-fleet-chaos"),
            threading.Thread(target=promote, daemon=True,
                             name="loadgen-fleet-promote")]
    for t in side:
        t.start()

    # ---- the scale-event timeline (ISSUE 17) ----
    # samples the router's own counters so the grew-BEFORE-shed assert
    # compares times from one clock, not inferred ordering
    scale_watch: dict = {}
    if autoscaler is not None:

        def scale_watcher():
            while not stop.is_set():
                if ("first_shed_at_s" not in scale_watch
                        and router.count("fleet_shed") > 0):
                    scale_watch["first_shed_at_s"] = round(
                        time.monotonic() - t_start, 2)
                if ("first_scale_event_at_s" not in scale_watch
                        and router.count("fleet_scale_events") > 0):
                    scale_watch["first_scale_event_at_s"] = round(
                        time.monotonic() - t_start, 2)
                stop.wait(0.1)

        threading.Thread(target=scale_watcher, daemon=True,
                         name="loadgen-fleet-scalewatch").start()

    # ---- the SLO alert watcher (ISSUE 16, --slo-report) ----
    slo_thread = None
    slo_timeline: dict = {}
    if args.slo_report and router.slo is not None:

        def slo_watch():
            # record the alert state machine live: the first firing and
            # the resolution that must follow once the burst's bad
            # events age out of the slow window
            deadline = time.monotonic() + args.duration + 75.0
            while time.monotonic() < deadline:
                firing = router.slo.firing()
                now_s = round(time.monotonic() - t_start, 2)
                if firing and "fired_at_s" not in slo_timeline:
                    slo_timeline["fired_at_s"] = now_s
                    slo_timeline["fired"] = [
                        {"objective": f["objective"], "rule": f["rule"],
                         "fire_count": f["fire_count"]}
                        for f in firing]
                if not firing and "fired_at_s" in slo_timeline:
                    slo_timeline["resolved_at_s"] = now_s
                    return
                time.sleep(0.2)

        slo_thread = threading.Thread(target=slo_watch, daemon=True,
                                      name="loadgen-slo-watch")
        slo_thread.start()

    # the X-Request-Id / idempotency-key contract through the router:
    # an explicit trace id must ride every attempt and echo back
    probe_trace = None
    try:
        _s, _p, probe_meta = router.dispatch(
            dict(bodies[0]), timeout_ms=args.timeout_ms,
            trace_id="loadgen-probe-1")
        probe_trace = probe_meta["trace_id"] if _s == 200 else (
            f"ERROR: status {_s}")
        if _s == 200:
            with stats.lock:
                stats.submitted += 1
                stats.answered += 1
                stats.trace_ids.add(probe_trace)
    except Exception as e:  # noqa: BLE001 — reported as a failure
        probe_trace = f"ERROR: {e!r}"

    # mid-load scrape of the ROUTER's /metrics plane (fleet counters +
    # replica-labeled gauge families + latency summaries)
    scrape: dict = {}

    def mid_scrape():
        stop.wait(args.duration * 0.6)
        from cgnn_tpu.observe.export import parse_prometheus_text

        text = router.registry.prometheus_text()
        scrape["text_bytes"] = len(text)
        try:
            fams = parse_prometheus_text(text)
            scrape["parse_ok"] = True
            scrape["missing_families"] = [
                p for p in ("cgnn_fleet_", "cgnn_replica_")
                if not any(f.startswith(p) for f in fams)
            ]
        except ValueError as e:
            scrape["parse_ok"] = False
            scrape["parse_error"] = str(e)

    scraper = threading.Thread(target=mid_scrape, daemon=True,
                               name="loadgen-fleet-scrape")
    if not args.no_scrape:
        scraper.start()

    # run until the duration elapsed AND the chaos legs finished (a
    # restart's boot may outlast a short duration — the victim must
    # still get post-restart traffic before the clients stop). The
    # continual loop also holds the load open: the canary needs live
    # labeled traffic flowing while candidates evaluate
    while True:
        elapsed = time.monotonic() - t_start
        if (elapsed >= args.duration and chaos_done.is_set()
                and promote_done.is_set()
                and continual_done.is_set()):
            break
        time.sleep(0.1)
    if chaos_log.get("restart_ready"):
        time.sleep(3.0)  # post-restart grace: let the probed-in victim
        #                  actually answer some of the closing traffic
    stop.set()
    for t in threads:
        t.join(timeout=args.timeout_ms / 1000.0 + 60.0)
    for t in side:
        t.join(timeout=120.0)
    for t in labeler_threads:
        # drains the queued labels (the pop bypasses the delay once
        # stop is set) so the exactly-once ledger closes complete
        t.join(timeout=60.0)
    if scraper.is_alive():
        scraper.join(timeout=30.0)
    wall = time.monotonic() - t_start
    # quiesce the self-driving layer BEFORE the router stops: the
    # remediator must not act on teardown noise, and autoscaler.stop()
    # joins any scale-down drain still in flight
    if remediator is not None:
        remediator.stop()
    if autoscaler is not None:
        autoscaler.stop()
    if canary_ctl is not None:
        canary_ctl.stop()
    if cont_proc is not None:
        if cont_proc.poll() is None:
            cont_proc.terminate()
        try:
            cont_proc.wait(timeout=120.0)
        except subprocess.TimeoutExpired:
            cont_proc.kill()
            cont_proc.wait(timeout=30.0)
        continual_log["trainer_exit"] = cont_proc.returncode
    slo_report: dict = {}
    if slo_thread is not None:
        # the resolve leg may land AFTER the load ends (the router's
        # tsdb heartbeat keeps evaluating with zero traffic), so wait
        # for the watcher BEFORE stopping the router; the quiesced
        # histogram truth check also needs the replicas still serving
        # their /metrics plane
        slo_thread.join(timeout=90.0)
        slo_report["alert"] = dict(slo_timeline)
        slo_report["engine"] = router.slo.state()
        slo_report.update(_fleet_hist_check(router, procs, stats))
        if recorder is not None:
            recorder.wait_idle(timeout_s=60.0)
            slo_report["flightrec"] = recorder.stats()
            slo_report["slo_bundles"] = _slo_bundle_manifests(
                flightrec_dir)
    if label_httpd is not None:
        label_httpd.shutdown()
        label_httpd.server_close()
    router.stop()
    router_stats = router.stats()
    if chaos_log.get("restart_ready"):
        chaos_log["victim_answered_at_end"] = (
            replicas[victim].counts["answered"])
    if args.expect_cachepart or args.kill_owner:
        # final replica-side cache counters (replicas still serving):
        # the dup-miss==0 and recovery assertions read these
        cachepart_log["counters_at_end"] = _fleet_cache_counts()
        chaos_log["cachepart"] = cachepart_log

    # ---- the cross-process trace join (ISSUE 15), BEFORE the
    # replicas drain away: router ring + every reachable replica's
    # /trace window -> one Perfetto file + the machine-checkable index
    observe_report: dict = {}
    if args.trace_ring:
        from cgnn_tpu.observe import trace_join

        windows, collect_errors = trace_join.collect_windows(
            router.replica_trace_urls())
        joined_path = os.path.splitext(os.path.abspath(args.report))[0] \
            + "_trace.json"
        doc = trace_join.write_joined(
            joined_path, [router.trace_window(), *windows])
        cross = trace_join.cross_process_traces(doc)
        observe_report = {
            "trace_joined": joined_path,
            "windows": 1 + len(windows),
            "collect_errors": collect_errors,
            "incomplete_processes": doc["incomplete_processes"],
            "traces_indexed": len(doc["traces"]),
            "cross_process_requests": len(cross),
        }
        if recorder is not None:
            recorder.wait_idle(timeout_s=60.0)
            frs = recorder.stats()
            observe_report["flightrec"] = frs
            if frs["last_bundle"]:
                # scan EVERY bundle's joined trace, not just the last:
                # the kill-instant breaker_trip bundle can legitimately
                # predate the first completed retry (its join then holds
                # no cross-process request yet); the ~0.5 s-later
                # replica_unreachable bundle is the deterministic one
                bundle_cross_max = 0
                try:
                    bundle_dirs = sorted(
                        os.path.join(flightrec_dir, d)
                        for d in os.listdir(flightrec_dir)
                        if d.startswith("bundle-"))
                except OSError:
                    bundle_dirs = [frs["last_bundle"]]
                for bdir in bundle_dirs:
                    try:
                        with open(os.path.join(bdir, "trace.json")) as f:
                            bundle_cross_max = max(
                                bundle_cross_max,
                                len(trace_join.cross_process_traces(
                                    json.load(f))))
                    except (OSError, ValueError) as e:
                        observe_report["bundle_trace_error"] = repr(e)
                observe_report["bundle_files"] = sorted(
                    os.listdir(frs["last_bundle"]))
                observe_report["bundle_cross_process_requests"] = (
                    bundle_cross_max)
    exit_codes = [p.terminate(timeout_s=60.0) for p in procs]
    # replicas the autoscaler booted (routed replacements + warm pool
    # spares) drain separately — 75 is the preemption-clean exit
    autoscaled_exits: dict = {}
    if autoscaler is not None:
        for rid in autoscaler.stats()["owned"]:
            if rid >= n:
                pr = autoscaler.proc_for(rid)
                if pr is not None:
                    autoscaled_exits[str(rid)] = pr.terminate(
                        timeout_s=60.0)

    lat = np.asarray(stats.latencies) if stats.latencies else np.zeros(1)
    with stats.lock:
        rejected_total = sum(stats.rejected.values())
    lost = (stats.submitted - stats.answered - rejected_total
            - len(stats.errors))
    report = {
        "mode": "fleet",
        "clients": args.clients,
        "replicas": n,
        "duration_s": round(wall, 2),
        "submitted": stats.submitted,
        "answered": stats.answered,
        "rejected": stats.rejected,
        "dropped": max(lost, 0),
        "client_errors": stats.errors[:10],
        "throughput_rps": round(stats.answered / wall, 1),
        "latency_ms": {
            "p50": float(np.percentile(lat, 50)),
            "p95": float(np.percentile(lat, 95)),
            "p99": float(np.percentile(lat, 99)),
            "mean": float(lat.mean()),
        },
        "param_versions": stats.versions,
        "devices": {
            "requested": str(n),
            "engine": "fleet",
            "count": n,
            "responses_by_device": {
                str(k): v
                for k, v in sorted(stats.device_responses.items())
            },
            "versions_by_device": {
                str(k): sorted(v)
                for k, v in sorted(stats.device_versions.items())
            },
        },
        "tracing": {
            "unique_trace_ids": len(stats.trace_ids),
            "missing_trace_ids": stats.missing_trace,
            "flushes_observed": 0,
            "probe_trace_id": probe_trace,
        },
        "fleet": {
            "chaos": chaos_log,
            "victim": victim,
            "replica_faults": args.replica_faults,
            "faulty_replica": (args.faulty_replica % n
                               if args.replica_faults else None),
            "attempts_hist": dict(sorted(
                fleet_counts["attempts_hist"].items())),
            "hedged_answers": fleet_counts["hedged_answers"],
            "retried_answers": fleet_counts["retried_answers"],
            "replica_exit_codes": exit_codes,
            "router": router_stats,
            "observe": observe_report,
        },
    }
    if plan is not None:
        report["priority"] = _priority_report(stats, plan)
    if scrape:
        report["fleet"]["metrics_scrape"] = scrape
    if slo_report:
        report["fleet"]["slo"] = slo_report
    if autoscaler is not None:
        a_stats = autoscaler.stats()
        # events carry t_s relative to the autoscaler's own birth;
        # t0_offset_s maps them onto the load timeline (t_start = 0)
        a_stats["t0_offset_s"] = round(asc_t0_mono - t_start, 3)
        a_stats.update(scale_watch)
        a_stats["exit_codes"] = autoscaled_exits
        report["fleet"]["autoscale"] = a_stats
    if remediator is not None:
        rem_stats = remediator.stats()
        rem_stats["journal"] = os.path.join(
            os.path.dirname(os.path.abspath(args.report)) or ".",
            "remediation.jsonl")
        report["fleet"]["remediation"] = rem_stats
    if journal is not None:
        labels_report = {k: v for k, v in label_log.items()
                         if k != "post_errors"}
        labels_report["post_errors"] = label_log["post_errors"][:10]
        labels_report["journal"] = journal.stats()
        labels_report["journal_path"] = (journal_path
                                         if args.continual else "")
        report["fleet"]["labels"] = labels_report
        journal.close()
    if args.continual:
        if recorder is not None:
            recorder.wait_idle(timeout_s=60.0)
        rb = continual_log.get("rolled_back", "")
        bundles = []
        if rb:
            import glob

            bundles = sorted(glob.glob(os.path.join(
                flightrec_dir, f"bundle-*canary_rollback_{rb}")))
        continual_log["rollback_bundle"] = bundles[-1] if bundles else ""
        cstats = canary_ctl.stats()
        report["fleet"]["continual"] = {
            **continual_log,
            "events": cstats["events"],
            "rejected": cstats["rejected"],
            "shadow_sent": cstats["shadow_sent"],
            "shadow_errors": cstats["shadow_errors"],
            "trainer_log": cont_log_path,
        }
        canary_mgr.close()
    return report


def _run_http(args) -> dict:
    """Minimal HTTP leg (urllib threads): smoke the wire path."""
    import urllib.request

    import numpy as np

    from cgnn_tpu.config import DataConfig
    from cgnn_tpu.data.dataset import load_synthetic
    from cgnn_tpu.data.rawbatch import raw_from_graph

    want_raw = args.wire in ("raw", "mixed")
    pool = load_synthetic(
        min(args.structures, 64),
        DataConfig(radius=6.0, max_num_nbr=12).featurize_config(),
        seed=args.seed + 1,
        keep_geometry=want_raw,
    )
    # wire-form request bodies (ISSUE 11): the ~100x smaller encoding a
    # raw-wire client ships — positions/lattice/species only
    raw_bodies = []
    if want_raw:
        for g in pool:
            r = raw_from_graph(g)
            if r is not None:
                raw_bodies.append({
                    "frac_coords": r.frac_coords.tolist(),
                    "lattice": r.lattice.tolist(),
                    "numbers": r.numbers.tolist(),
                    "id": r.cif_id,
                })
    stats = _ClientStats()
    stop = threading.Event()

    base = args.http.rstrip("/")

    def client(ci: int):
        rng = np.random.default_rng(args.seed + ci)
        raw_share = {"featurized": 0.0, "mixed": 0.5, "raw": 1.0}[args.wire]
        while not stop.is_set():
            # allow_nan=False, not jsonfinite(): features are finite by
            # construction, and the recursive rebuild in N client hot
            # loops would skew the rps/p99 this tool exists to measure
            if raw_bodies and rng.random() < raw_share:
                payload_body = {"structure": raw_bodies[
                    int(rng.integers(len(raw_bodies)))]}
            else:
                g = pool[int(rng.integers(len(pool)))]
                payload_body = {"graph": {
                    "atom_fea": g.atom_fea.tolist(),
                    "edge_fea": g.edge_fea.tolist(),
                    "centers": g.centers.tolist(),
                    "neighbors": g.neighbors.tolist(),
                    "id": g.cif_id,
                }}
            body = json.dumps({**payload_body,
                               "timeout_ms": args.timeout_ms},
                              allow_nan=False).encode()
            req = urllib.request.Request(
                base + "/predict", data=body,
                headers={"Content-Type": "application/json"},
            )
            with stats.lock:
                stats.submitted += 1
            try:
                with urllib.request.urlopen(
                    req, timeout=args.timeout_ms / 1000.0 + 30.0
                ) as resp:
                    payload = json.loads(resp.read())
            except Exception as e:  # noqa: BLE001 — count and move on
                with stats.lock:
                    reason = getattr(e, "code", "transport")
                    stats.rejected[str(reason)] = (
                        stats.rejected.get(str(reason), 0) + 1
                    )
                continue
            with stats.lock:
                stats.answered += 1
                stats.latencies.append(float(payload["latency_ms"]))
                v = payload["param_version"]
                stats.versions[v] = stats.versions.get(v, 0) + 1
                tid = payload.get("trace_id", "")
                if tid:
                    stats.trace_ids.add(tid)
                else:
                    stats.missing_trace += 1
                fid = payload.get("flush_id", "")
                if fid:
                    stats.flush_ids.add(fid)
                w = payload.get("wire", "featurized")
                stats.wire_responses[w] = stats.wire_responses.get(w, 0) + 1

    # mid-load wire-path plane checks (GET /metrics, POST /profile) —
    # fired against the LIVE server while the clients keep hammering it
    scrape_result: dict = {}
    profile_result: dict = {}

    def mid_scrape():
        time.sleep(args.duration * 0.6)
        try:
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=30.0) as resp:
                text = resp.read().decode()
            scrape_result.update(text=text, text_bytes=len(text),
                                 at_s=round(time.monotonic() - t_start, 2),
                                 measured_now_p99=_measured_p99(stats))
        except Exception as e:  # noqa: BLE001 — reported as a failure
            scrape_result.update(error=repr(e))

    def mid_profile():
        time.sleep(args.duration * 0.4)
        req = urllib.request.Request(
            base + "/profile",
            data=json.dumps({"duration_ms": 500}, allow_nan=False).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60.0) as resp:
                profile_result.update(json.loads(resp.read()))
        except Exception as e:  # noqa: BLE001 — reported as a failure
            profile_result.update(ok=False, error=repr(e))

    threads = [threading.Thread(target=client, args=(i,), daemon=True,
                                name=f"loadgen-http-client-{i}")
               for i in range(args.clients)]
    checkers = []
    if not args.no_scrape:
        checkers.append(threading.Thread(target=mid_scrape, daemon=True,
                                         name="loadgen-scrape"))
    if args.profile_mid:
        checkers.append(threading.Thread(target=mid_profile, daemon=True,
                                         name="loadgen-profile"))
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in checkers:
        t.start()

    # the X-Request-Id contract, over the wire: a probe's inbound header
    # must come back as its trace id (response body AND echo header).
    # Bounded retries on TRANSPORT errors only: under a CPU-bound burst
    # a connection can be refused/reset before the listener accepts it —
    # that is load-shedding noise, not the header-echo contract this
    # probe pins (HTTP rejections still fail it immediately).
    probe_trace = None
    g = pool[0]
    req = urllib.request.Request(
        base + "/predict",
        data=json.dumps({"graph": {
            "atom_fea": g.atom_fea.tolist(),
            "edge_fea": g.edge_fea.tolist(),
            "centers": g.centers.tolist(),
            "neighbors": g.neighbors.tolist(),
        }, "timeout_ms": args.timeout_ms}, allow_nan=False).encode(),
        headers={"Content-Type": "application/json",
                 "X-Request-Id": "loadgen-probe-1"},
    )
    for attempt in range(4):
        try:
            with urllib.request.urlopen(
                req, timeout=args.timeout_ms / 1000.0 + 30.0
            ) as resp:
                payload = json.loads(resp.read())
                header_echo = resp.headers.get("X-Request-Id")
            probe_trace = payload.get("trace_id")
            if header_echo != probe_trace:
                probe_trace = (f"ERROR: body {probe_trace!r} != header "
                               f"{header_echo!r}")
            break
        except (ConnectionError, OSError) as e:
            probe_trace = f"ERROR: {e!r}"
            time.sleep(1.0 + attempt)
        except Exception as e:  # noqa: BLE001 — reported as a failure
            probe_trace = f"ERROR: {e!r}"
            break

    time.sleep(max(0.0, args.duration - (time.monotonic() - t_start)))
    stop.set()
    for t in threads:
        t.join(timeout=60.0)
    for t in checkers:
        t.join(timeout=60.0)
    wall = time.monotonic() - t_start
    lat = np.asarray(stats.latencies) if stats.latencies else np.zeros(1)
    report = {
        "mode": "http",
        "clients": args.clients,
        "duration_s": round(wall, 2),
        "submitted": stats.submitted,
        "answered": stats.answered,
        "rejected": stats.rejected,
        "dropped": 0,
        "throughput_rps": round(stats.answered / wall, 1),
        "latency_ms": {
            "p50": float(np.percentile(lat, 50)),
            "p99": float(np.percentile(lat, 99)),
        },
        "param_versions": stats.versions,
        "wire": {
            "requested": args.wire,
            "responses_by_wire": dict(sorted(
                stats.wire_responses.items())),
            "raw_pool": len(raw_bodies),
            "probes": {},
        },
        "tracing": {
            "unique_trace_ids": len(stats.trace_ids),
            "missing_trace_ids": stats.missing_trace,
            "flushes_observed": len(stats.flush_ids),
            "probe_trace_id": probe_trace,
        },
    }
    if scrape_result:
        scraped_p99 = 0.0
        if "text" in scrape_result:
            from cgnn_tpu.observe.export import parse_prometheus_text

            try:
                fams = parse_prometheus_text(scrape_result["text"])
                for name, value in fams.get(
                        "cgnn_serve_latency_ms", {}).get("samples", []):
                    if 'quantile="0.99"' in name:
                        scraped_p99 = value
            except ValueError:
                pass
            report["metrics_scrape"] = {
                "at_s": scrape_result["at_s"],
                "text_bytes": scrape_result["text_bytes"],
                "final_measured_p99_ms": _measured_p99(stats),
                **_scrape_check(scrape_result["text"], scraped_p99,
                                scrape_result.get("measured_now_p99", 0.0),
                                args.scrape_tolerance),
            }
        else:
            report["metrics_scrape"] = {"parse_ok": False,
                                        **scrape_result}
    if profile_result:
        report["profile"] = profile_result
    return report


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.make_ckpt:
        make_synth_ckpt(args.make_ckpt, seed=args.seed)
        return 0
    if not args.http and not args.ckpt_dir:
        print("CKPT_DIR (or --http URL / --make-ckpt DIR) required",
              file=sys.stderr)
        return 2
    if (args.continual or args.label_feedback > 0) and not args.fleet:
        print("--continual / --label-feedback need --fleet N",
              file=sys.stderr)
        return 2
    if args.continual and not args.trace_ring:
        print("--continual needs the flight recorder (--trace-ring > 0)",
              file=sys.stderr)
        return 2
    if args.priority_mix and args.http:
        print("--priority-mix drives the in-proc or --fleet modes "
              "(the bare --http leg has no class accounting)",
              file=sys.stderr)
        return 2

    if args.fleet:
        report = _run_fleet(args)
    elif args.http:
        report = _run_http(args)
    else:
        report = _run_inproc(args)

    failures = []
    if report.get("dropped"):
        failures.append(f"{report['dropped']} dropped responses (must be 0)")
    if report.get("client_errors"):
        failures.append(f"client errors: {report['client_errors']}")
    if report.get("compiles", {}).get("after_warm"):
        failures.append(
            f"{report['compiles']['after_warm']} recompiles after warmup "
            f"(must be 0)"
        )
    tracing = report.get("tracing", {})
    if tracing:
        if tracing["missing_trace_ids"]:
            failures.append(
                f"{tracing['missing_trace_ids']} responses carried no "
                f"trace id (every response must)"
            )
        if (report["answered"]
                and tracing["unique_trace_ids"] != report["answered"]):
            failures.append(
                f"trace ids not unique: {tracing['unique_trace_ids']} "
                f"distinct over {report['answered']} answered (co-batched "
                f"requests must carry DISTINCT ids)"
            )
        if tracing["probe_trace_id"] != "loadgen-probe-1":
            failures.append(
                f"X-Request-Id probe not honored: sent 'loadgen-probe-1', "
                f"got {tracing['probe_trace_id']!r}"
            )
    scrape = report.get("metrics_scrape")
    if scrape is not None:
        if not scrape.get("parse_ok"):
            failures.append(
                f"/metrics scrape did not parse as Prometheus exposition "
                f"format: {scrape.get('parse_error', scrape.get('error'))}"
            )
        elif scrape.get("missing_families"):
            failures.append(
                f"/metrics missing required metric families: "
                f"{scrape['missing_families']}"
            )
        elif report["answered"] >= 100 and not scrape.get("agree"):
            failures.append(
                f"scraped rolling p99 {scrape['scraped_p99_ms']:.1f} ms "
                f"disagrees with measured p99 "
                f"{scrape['measured_p99_ms']:.1f} ms beyond tolerance "
                f"{scrape['tolerance_ms']} ms"
            )
    if args.profile_mid:
        prof = report.get("profile", {})
        if not prof.get("ok", prof.get("bytes", 0) > 0):
            failures.append(f"mid-load profile capture failed: {prof}")
        elif not prof.get("bytes"):
            failures.append(
                f"mid-load profile capture wrote an EMPTY artifact: {prof}"
            )
    wire = report.get("wire", {})
    if args.wire in ("raw", "mixed"):
        by_wire = wire.get("responses_by_wire", {})
        if not by_wire.get("raw"):
            failures.append(
                f"raw wire requested but no raw-wire responses: {by_wire}"
            )
        if args.wire == "mixed" and not by_wire.get("featurized"):
            failures.append(
                f"mixed wire load produced no featurized responses "
                f"(form-boundary cut unexercised): {by_wire}"
            )
    if not args.http and args.wire in ("raw", "mixed"):
        probes = wire.get("probes", {})
        if "parity" in probes and not probes["parity"].get("ok"):
            failures.append(f"raw-vs-featurized parity probe failed: "
                            f"{probes['parity']}")
        if args.raw_overflow_probe:
            if not probes.get("overflow", {}).get("ok"):
                failures.append(f"in-program overflow probe failed: "
                                f"{probes.get('overflow')}")
            ovf = (report.get("server_stats", {}).get("ingest", {})
                   .get("cap_overflows", 0))
            if not ovf:
                failures.append(
                    "overflow probe ran but ingest_cap_overflow_total "
                    "never incremented"
                )
        else:
            # the satellite invariant: on a CALIBRATED ladder with the
            # host pre-check on, the in-program flag must never fire
            ovf = (report.get("server_stats", {}).get("ingest", {})
                   .get("cap_overflows", 0))
            if ovf:
                failures.append(
                    f"{ovf} in-program cap overflows on the calibrated "
                    f"ladder (pre-check on: must be 0)"
                )
    if args.hot_swap and not args.http:
        versions = report["param_versions"]
        if report["hot_swap"]["watcher_swaps"] < 1:
            failures.append("hot swap never happened")
        elif len([v for v, c in versions.items() if c > 0]) < 2:
            failures.append(
                f"expected responses from both param versions, saw "
                f"{versions}"
            )
    if not args.http and args.devices != "auto" and int(args.devices) > 1:
        # forced multi-device dryrun (ISSUE 5): distribution is a HARD
        # invariant — a device that answered nothing under sustained
        # load means the router (or the replica set) is broken
        dev = report["devices"]
        want = int(args.devices)
        if dev["count"] != want:
            failures.append(
                f"requested {want} devices, server resolved {dev['count']}"
            )
        silent = [i for i in range(want)
                  if not dev["responses_by_device"].get(str(i))]
        if silent:
            failures.append(
                f"devices {silent} answered no responses under load "
                f"(distribution broken: {dev['responses_by_device']})"
            )
    if args.fleet:
        # ---- the fleet chaos invariants (ISSUE 14), all HARD ----
        fl = report["fleet"]
        rc = fl["router"]["counts"]
        chaos = fl["chaos"]
        hard_rejects = dict(report["rejected"])
        if args.priority_mix:
            # deadline-feasibility sheds (ISSUE 19) are load shedding,
            # not loss (INVARIANTS.md): under a mixed-priority leg the
            # router MAY 429/504 an infeasible request before it
            # crosses a process boundary — the exactly-once ledger
            # still closes (shed requests are typed rejections)
            for reason in ("infeasible_queue", "infeasible_deadline"):
                hard_rejects.pop(reason, None)
        if hard_rejects:
            failures.append(
                f"fleet rejected requests (with {args.fleet} replicas "
                f"and retries these legs must answer everything): "
                f"{hard_rejects}"
            )
        if rc.get("fleet_exhausted"):
            failures.append(
                f"{rc['fleet_exhausted']} requests exhausted every "
                f"attempt (accepted-then-lost; must be 0)"
            )
        if rc.get("fleet_deadline_exceeded"):
            failures.append(
                f"{rc['fleet_deadline_exceeded']} requests blew the "
                f"fleet deadline (must be 0 at smoke load)"
            )
        if rc.get("fleet_duplicate_answers"):
            failures.append(
                f"{rc['fleet_duplicate_answers']} duplicate answers — "
                f"the exactly-once invariant is broken"
            )
        silent = [i for i in range(args.fleet)
                  if not report["devices"]["responses_by_device"]
                  .get(str(i))]
        if silent:
            failures.append(
                f"replicas {silent} answered nothing under load: "
                f"{report['devices']['responses_by_device']}"
            )
        if args.kill_at > 0:
            if "killed_at_s" not in chaos:
                failures.append("kill leg requested but never fired")
            elif not rc.get("fleet_transport_errors"):
                failures.append(
                    "kill -9 fired but the router saw no transport "
                    "errors — the chaos leg did not actually bite"
                )
        if args.restart_at > 0:
            if not chaos.get("restart_ready"):
                failures.append(
                    f"restarted replica {fl['victim']} never became "
                    f"ready again: {chaos}"
                )
            else:
                before = chaos.get("victim_answered_at_restart", 0)
                after = chaos.get("victim_answered_at_end", 0)
                if after <= before:
                    failures.append(
                        f"restarted replica {fl['victim']} was never "
                        f"probed back into rotation (answered {before} "
                        f"-> {after})"
                    )
                br = (fl["router"]["replicas"]
                      .get(str(fl["victim"]), {})
                      .get("breaker", {}))
                if br.get("state") != "closed":
                    failures.append(
                        f"victim breaker not re-closed after restart: "
                        f"{br}"
                    )
        if args.promote_at > 0:
            if "promotion_error" in chaos:
                failures.append(
                    f"promotion leg failed: {chaos['promotion_error']}")
            else:
                if not chaos.get("promotion_consistent"):
                    failures.append(
                        f"fleet never converged on the promoted "
                        f"version: {chaos.get('final_versions')}"
                    )
                if len([v for v, c in report["param_versions"].items()
                        if c > 0]) < 2:
                    failures.append(
                        f"rolling promotion should have answered from "
                        f"BOTH versions mid-roll, saw "
                        f"{report['param_versions']}"
                    )
        if args.expect_retries and not rc.get("fleet_retries"):
            failures.append(
                "expected router retries (--expect-retries) but none "
                "happened"
            )
        if args.expect_hedges and not rc.get("fleet_hedges"):
            failures.append(
                "expected hedged requests (--expect-hedges) but none "
                "fired"
            )
        if args.expect_cachepart:
            # ---- the one-fleet-cache invariants (ISSUE 20) ----
            if not rc.get("fleet_fingerprinted"):
                failures.append(
                    "cachepart leg: the router fingerprinted no "
                    "request — edge hashing never engaged")
            if not rc.get("fleet_owner_routed"):
                failures.append(
                    "cachepart leg: owner-affinity never routed a "
                    "request to its ring owner")
            cp = chaos.get("cachepart", {})
            if args.kill_owner:
                ob = cp.get("owner_before")
                od = cp.get("owner_during_kill")
                oa = cp.get("owner_after_restart")
                if ob is None:
                    failures.append(
                        f"cachepart leg: no ring owner recorded for "
                        f"the hot key: {cp}")
                elif args.kill_at > 0 and (od is None or od == ob):
                    failures.append(
                        f"cachepart leg: the killed owner's arcs never "
                        f"re-owned to a survivor (owner {ob} -> {od})")
                if args.restart_at > 0 and oa != ob:
                    failures.append(
                        f"cachepart leg: re-ownership did not revert "
                        f"after the restart (owner {ob} -> {oa}; the "
                        f"ring must restore the original mapping)")
            end = cp.get("counters_at_end", {})
            if end.get("cache_dup_misses"):
                failures.append(
                    f"cachepart leg: {end['cache_dup_misses']} "
                    f"duplicate in-flight misses fleet-wide — "
                    f"single-flight must hold this at exactly 0")
            base = cp.get("counters_at_restart") or {}
            d_req = (end.get("requests", 0) - base.get("requests", 0))
            d_hit = (end.get("cache_hits", 0)
                     + end.get("cache_coalesced", 0)
                     - base.get("cache_hits", 0)
                     - base.get("cache_coalesced", 0))
            ratio = d_hit / d_req if d_req > 0 else 0.0
            if d_req <= 0:
                failures.append(
                    "cachepart leg: no post-restart traffic reached "
                    "the replicas — hit-ratio recovery unmeasurable")
            elif ratio < 0.5:
                failures.append(
                    f"cachepart leg: fleet hit ratio did not recover "
                    f"after the restart ({ratio:.2%} effective over "
                    f"{d_req} requests; want >= 50% on the Zipf "
                    f"keyset)")
        if args.label_feedback > 0 or args.continual:
            # ---- the exactly-once label-join ledger (ISSUE 18) ----
            lb = fl.get("labels", {})
            js = lb.get("journal", {})
            if lb.get("post_errors"):
                failures.append(
                    f"label POSTs errored: {lb['post_errors']}")
            if not lb.get("sent"):
                failures.append(
                    "label feedback requested but no label was ever "
                    "POSTed")
            if lb.get("joined") != lb.get("sent"):
                failures.append(
                    f"label joins incomplete: {lb.get('joined')} "
                    f"joined of {lb.get('sent')} sent (every first "
                    f"POST must land exactly once)")
            if lb.get("unmatched"):
                failures.append(
                    f"{lb['unmatched']} labels joined NOTHING (every "
                    f"label targets a journaled answer)")
            if lb.get("resend_not_already"):
                failures.append(
                    f"{lb['resend_not_already']} deliberate label "
                    f"re-POSTs did NOT answer 'already' — the "
                    f"exactly-once join is broken")
            if js.get("duplicate_joins") != lb.get("double_posts"):
                failures.append(
                    f"journal duplicate_joins "
                    f"{js.get('duplicate_joins')} != deliberate "
                    f"re-POSTs {lb.get('double_posts')} (a duplicate "
                    f"apply slipped through, or one was double-counted)"
                )
            if js.get("served") != report["answered"]:
                failures.append(
                    f"journal holds {js.get('served')} served records "
                    f"for {report['answered']} answered requests "
                    f"(exactly one record per answer — hedged and "
                    f"retried attempts share the trace id)")
        if args.continual:
            # ---- the closed continual loop (ISSUE 18), all HARD ----
            cont = fl.get("continual", {})
            commits = cont.get("commits", [])
            if len(commits) < 2:
                failures.append(
                    f"continual trainer committed {len(commits)} "
                    f"candidate(s); the leg needs its clean round AND "
                    f"its corrupted one (trainer log: "
                    f"{cont.get('trainer_log')})")
            if not cont.get("promoted"):
                failures.append(
                    "no candidate was ever promoted fleet-wide")
            else:
                if commits and cont["promoted"] != commits[0]:
                    failures.append(
                        f"promoted {cont['promoted']} but the first "
                        f"(clean) candidate was {commits[0]}")
                if not cont.get("promotion_consistent"):
                    failures.append(
                        f"fleet never converged on the promoted "
                        f"candidate {cont['promoted']}")
                if not report["param_versions"].get(cont["promoted"]):
                    failures.append(
                        f"promoted candidate {cont['promoted']} never "
                        f"answered live traffic: "
                        f"{report['param_versions']}")
            if not cont.get("rolled_back"):
                failures.append(
                    "the corrupted candidate was never rolled back")
            else:
                if (len(commits) >= 2
                        and cont["rolled_back"] != commits[1]):
                    failures.append(
                        f"rolled back {cont['rolled_back']} but the "
                        f"corrupted candidate was {commits[1]}")
                if not cont.get("rollback_bundle"):
                    failures.append(
                        f"rollback of {cont['rolled_back']} dumped no "
                        f"flight-recorder bundle naming it")
            if cont.get("trainer_exit") not in (0, 75):
                failures.append(
                    f"continual trainer exited "
                    f"{cont.get('trainer_exit')} (log: "
                    f"{cont.get('trainer_log')})")
        # exits 0 (drained) and 75 (resumable preemption, PR 2) are
        # both clean; a remediated victim was force-reaped on purpose
        remediated = {a.get("replica") for a in
                      fl.get("remediation", {}).get("actions", [])}
        codes = fl["replica_exit_codes"]
        bad_exits = [
            (i, c) for i, c in enumerate(codes)
            if c not in (0, 75) and i not in remediated
            and not (i == fl["victim"] and args.kill_at > 0
                     and args.restart_at == 0)
        ]
        if bad_exits:
            failures.append(
                f"replica drain exits non-zero: {bad_exits} "
                f"(graceful SIGTERM drain must exit 0 or 75)"
            )
        for rid_s, c in (fl.get("autoscale", {}).get("exit_codes")
                         or {}).items():
            if c not in (0, 75) and int(rid_s) not in remediated:
                failures.append(
                    f"autoscaled replica {rid_s} drain exit {c} "
                    f"(must be 0 or 75)")
        if args.autoscale:
            # ---- the self-driving scaling invariants (ISSUE 17) ----
            auto = fl.get("autoscale", {})
            ac = auto.get("counts", {})
            if not ac.get("scale_ups"):
                failures.append(
                    "autoscale leg: the fleet never grew under the ramp")
            if args.ramp and not ac.get("scale_downs"):
                failures.append(
                    "autoscale leg: the fleet never shrank after the "
                    "ramp-down")
            if rc.get("fleet_shed"):
                # shedding is legitimate ONLY after growth was attempted:
                # the first scale-up must predate the first shed
                ups = [e for e in auto.get("events", [])
                       if e["action"] == "scale_up"]
                first_up = (ups[0]["t_s"] + auto.get("t0_offset_s", 0.0)
                            if ups else None)
                first_shed = auto.get("first_shed_at_s")
                if first_up is None or (first_shed is not None
                                        and first_up >= first_shed):
                    failures.append(
                        f"autoscaler shed before growing: first shed at "
                        f"{first_shed} s, first scale-up at {first_up} s "
                        f"({rc['fleet_shed']} shed)")
            if not rc.get("fleet_scale_events"):
                failures.append(
                    "no fleet scale events recorded (every drained "
                    "exit must be classified a scale event)")
            if rc.get("fleet_incidents") and not args.remediate:
                failures.append(
                    f"{rc['fleet_incidents']} fleet incident(s) during "
                    f"a pure scaling leg (planned drains must never "
                    f"count as incidents)")
        if args.remediate:
            # ---- the auto-remediation invariants (ISSUE 17) ----
            rem = fl.get("remediation", {})
            acts = rem.get("actions", [])
            if not acts:
                failures.append(
                    f"remediation leg: no action executed (policy: "
                    f"{rem.get('policy')})")
            else:
                a0 = acts[0]
                if not a0.get("bundle"):
                    failures.append(
                        "remediation action names no evidence bundle")
                repl = a0.get("replacement")
                if repl is None:
                    failures.append(
                        "remediation replace step failed (no "
                        "replacement replica booted)")
                elif not report["devices"]["responses_by_device"].get(
                        str(repl)):
                    failures.append(
                        f"replacement replica {repl} answered nothing "
                        f"after the swap: "
                        f"{report['devices']['responses_by_device']}")
                if str(a0.get("replica")) in fl["router"]["replicas"]:
                    failures.append(
                        f"remediated replica {a0.get('replica')} is "
                        f"still routed")
                jp = rem.get("journal", "")
                try:
                    with open(jp) as f:
                        entries = [json.loads(x) for x in f]
                except (OSError, ValueError):
                    entries = []
                if not entries:
                    failures.append(
                        f"remediation journal missing or empty: {jp!r}")
                elif not all(e.get("bundle") for e in entries):
                    failures.append(
                        "remediation journal entry missing its bundle "
                        "reference (every action must name its "
                        "evidence)")
            if not rc.get("fleet_incidents"):
                failures.append(
                    "wedge leg recorded no fleet incident (the "
                    "remediation removal must count as one)")
        scrape_fl = fl.get("metrics_scrape")
        if scrape_fl is not None:
            if not scrape_fl.get("parse_ok"):
                failures.append(
                    f"router /metrics did not parse: {scrape_fl}")
            elif scrape_fl.get("missing_families"):
                failures.append(
                    f"router /metrics missing families: "
                    f"{scrape_fl['missing_families']}"
                )
        if args.expect_trace_join:
            # ---- the ISSUE-15 cross-process observability asserts ----
            obs = fl.get("observe", {})
            if not obs:
                failures.append(
                    "trace join expected but the trace layer was off "
                    "(--trace-ring 0?)"
                )
            else:
                if obs.get("windows", 0) < 2:
                    failures.append(
                        f"joined trace covers {obs.get('windows')} "
                        f"process window(s); need the router plus at "
                        f"least one replica"
                    )
                if not obs.get("cross_process_requests"):
                    failures.append(
                        "joined fleet trace holds NO retried/hedged "
                        "request with spans from >= 2 processes (the "
                        "cross-process join is broken)"
                    )
                frs = obs.get("flightrec", {})
                if not frs.get("bundles"):
                    failures.append(
                        f"chaos leg produced no flight-recorder bundle "
                        f"(triggers seen: {frs.get('triggers')})"
                    )
                elif "trace.json" not in obs.get("bundle_files", []):
                    failures.append(
                        f"flight-recorder bundle is missing its joined "
                        f"trace: {obs.get('bundle_files')}"
                    )
                elif not obs.get("bundle_cross_process_requests"):
                    failures.append(
                        "flight-recorder bundle's joined trace holds "
                        "no retried/hedged request spanning >= 2 "
                        "processes"
                    )
                elif "requests.jsonl" not in obs.get("bundle_files", []):
                    failures.append(
                        f"flight-recorder bundle is missing the "
                        f"recent-request ring: {obs.get('bundle_files')}"
                    )
        if args.slo_report:
            # ---- the ISSUE-16 metrics-truth asserts, all HARD ----
            slo = fl.get("slo", {})
            if not slo:
                failures.append(
                    "--slo-report set but the SLO layer never ran "
                    "(router built without it?)"
                )
            else:
                if not slo.get("merge_bitexact"):
                    failures.append(
                        f"fleet-merged histograms are not bit-identical "
                        f"to pooling every replica's own scrape: "
                        f"{slo.get('merge_mismatches')}"
                    )
                lt = slo.get("latency_truth", {})
                if not lt.get("count_exact"):
                    failures.append(
                        f"router fleet latency histogram count != "
                        f"answered requests: {lt}"
                    )
                if not lt.get("count_covers_answered"):
                    failures.append(
                        f"merged replica latency histogram does not "
                        f"cover every answered request: {lt}"
                    )
                if not lt.get("p50_agree"):
                    failures.append(
                        f"merged-histogram median disagrees with the "
                        f"client-measured p50 beyond bucket resolution "
                        f"+ overhead margin: {lt}"
                    )
                alert = slo.get("alert", {})
                if "fired_at_s" not in alert:
                    failures.append(
                        "burn-rate alert never fired under the "
                        "injected 5xx burst"
                    )
                elif "resolved_at_s" not in alert:
                    failures.append(
                        f"burn-rate alert fired at "
                        f"{alert['fired_at_s']} s but never resolved"
                    )
                if "flightrec" in slo:
                    trig = slo["flightrec"].get("triggers", {})
                    if not any(k.startswith("slo_burn_") for k in trig):
                        failures.append(
                            f"firing SLO alert never triggered a "
                            f"flight-recorder dump (triggers: {trig})"
                        )
                    elif not slo.get("slo_bundles"):
                        failures.append(
                            "no flight-recorder bundle manifest names "
                            "an slo_burn_* trigger reason"
                        )
    if args.priority_mix:
        # ---- the mixed-priority invariants (ISSUE 19), all HARD ----
        from cgnn_tpu.serve.batcher import parse_kv_spec

        pr = report.get("priority", {})
        by_cls = pr.get("latency_ms_by_class", {})
        plan = _priority_plan(args)
        for c in sorted(plan["rates"]):
            if not pr.get("responses_by_class", {}).get(c):
                failures.append(
                    f"priority class {c!r} sent load but answered "
                    f"nothing: {pr.get('responses_by_class')}")
        for c, bound in sorted(parse_kv_spec(args.class_slo_ms).items()):
            got = by_cls.get(c, {}).get("p99")
            if got is None:
                failures.append(
                    f"--class-slo-ms names {c!r} but no latency was "
                    f"measured for it")
            elif got > bound:
                failures.append(
                    f"class {c!r} p99 {got:.1f} ms exceeds its "
                    f"{bound:.0f} ms SLO "
                    f"(over {by_cls[c]['count']} answers)")
        if args.expect_backfill:
            if not pr.get("backfilled_responses"):
                failures.append(
                    "--expect-backfill: no response ever rode a "
                    "higher-class flush's padding slack")
            if (not args.fleet
                    and not pr.get("padding_fill_share", 0.0) > 0.0):
                failures.append(
                    f"--expect-backfill: serve_padding_fill_share is "
                    f"{pr.get('padding_fill_share')} (must be > 0)")
    # racecheck leg (CGNN_TPU_RACECHECK=1): the runtime lock-discipline
    # report rides the SLO report and fails the run like any other
    # invariant — zero lock-order inversions, zero unguarded shared-field
    # touches, zero deadlock-watchdog dumps under the full client load.
    # In-proc ONLY: in --http mode the server runs in another process and
    # this process's racecheck state is empty — reporting that as "clean"
    # would be a vacuous verdict about a server never instrumented here.
    from cgnn_tpu.analysis import racecheck

    if args.http and racecheck.enabled():
        print("racecheck: gate is on but --http drives a remote process; "
              "no verdict (run the in-proc mode to instrument the server)")
    if racecheck.enabled() and not args.http:
        rc = racecheck.report()
        report["racecheck"] = rc
        if rc["inversions"]:
            failures.append(
                f"{len(rc['inversions'])} lock-order inversion(s): "
                f"{rc['inversions'][:3]}"
            )
        if rc["violations"]:
            failures.append(
                f"{len(rc['violations'])} unguarded shared-field "
                f"access(es): {rc['violations'][:3]}"
            )
        if rc["deadlock_dumps"]:
            failures.append(
                f"deadlock watchdog fired {rc['deadlock_dumps']} time(s) "
                f"(stalled: {rc['stalled_threads']})"
            )
        print(
            f"racecheck: {len(rc['inversions'])} inversions, "
            f"{len(rc['violations'])} violations, "
            f"{rc['deadlock_dumps']} watchdog dumps across "
            f"{len(rc['heartbeats_seen'])} heartbeating thread(s)"
        )
    report["failures"] = failures
    with open(args.report, "w") as f:
        json.dump(jsonfinite(report), f, indent=1)
    lat = report["latency_ms"]
    dev = report.get("devices", {})
    print(
        f"[{report['mode']}] {report['answered']}/{report['submitted']} "
        f"answered @ {report['throughput_rps']} rps | p50 "
        f"{lat['p50']:.1f} ms p99 {lat['p99']:.1f} ms | occupancy "
        f"{report.get('batch_occupancy_mean', 0):.2f} | versions "
        f"{report['param_versions']} | devices "
        f"{dev.get('responses_by_device', {})} | report -> {args.report}"
    )
    if failures:
        print("SLO INVARIANT FAILURES: " + "; ".join(failures),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
