#!/usr/bin/env python
"""Host-ingest micro-benchmark: pack + end-to-end inference rates.

The ISSUE-4 regression guard: BENCH_r05 showed the forward path 98.7%
host-bound (device 112,305 structs/s, end-to-end 1,461), and the fix —
compact staging + parallel packers + pooled buffers — lives entirely in
host code that CPU CI exercises faithfully. This script measures the
ingest path at a configurable scale and prints ONE JSON line::

    {"pack_structs_per_sec": ..., "e2e_structs_per_sec": ...,
     "bytes_staged": ..., ...extras}

- ``pack_structs_per_sec`` — the pipelined pack rate alone (graphs
  through plan -> parallel_pack -> packed batches, no device);
- ``e2e_structs_per_sec`` — ``run_fast_inference`` end to end (pack +
  dispatch + stacked fetch) with a tiny model, post-compile;
- ``bytes_staged`` — host bytes of the packed batches crossing the link
  (the compact-vs-full ~12x is visible here);
- ``serial_*`` twins measured on the pre-ISSUE-4 path (serial workers,
  full-fidelity staging) so a regression in EITHER the new machinery or
  the baseline is visible per-PR, like serve-smoke.

CI runs it at smoke scale (tier1.yml "ingest-bench" step); locally, push
``--n`` up to see the at-scale separation::

    JAX_PLATFORMS=cpu python scripts/ingest_bench.py --n 2048 --workers 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--n", type=int, default=512,
                   help="synthetic MP-like structures to ingest")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--workers", type=int, default=2,
                   help="pack pipeline threads")
    p.add_argument("--rungs", type=int, default=2)
    p.add_argument("--dense-m", type=int, default=12)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--repeats", type=int, default=3,
                   help="timed rounds per metric (best is reported)")
    p.add_argument("--devices", default="auto",
                   help="device-parallel e2e leg (ISSUE 5): 'auto' or a "
                        "forced count (the CI 8-host-device dryrun)")
    p.add_argument("--wire", choices=["both", "raw", "featurized"],
                   default="both",
                   help="ISSUE-11 raw-wire leg: measure bytes-on-wire "
                        "and host-ms/request for raw (positions/"
                        "lattice/species + in-program neighbor search) "
                        "vs compact vs full staging, parity-asserted; "
                        "'featurized' skips it (the pre-ISSUE-11 "
                        "output)")
    return p


def _tree_bytes(batch) -> int:
    import jax

    return sum(x.nbytes for x in jax.tree_util.tree_leaves(batch))


def _pack_all(graphs, shape_set, workers):
    """Pack the whole dataset through the pipeline; -> (seconds, bytes)."""
    from cgnn_tpu.data.pipeline import BufferPool, parallel_pack
    from cgnn_tpu.train.infer import _shape_set_plan

    pool = BufferPool() if shape_set.compact is not None else None

    def pack_job(job):
        _, sub, shape = job
        buf = None
        if pool is not None:
            key = shape_set.buffer_key(shape)
            buf = (key, pool.acquire(key, shape_set.buffer_factory(shape)))
        batch = shape_set.pack(sub, shape=shape,
                               out=None if buf is None else buf[1])
        # byte count returned, summed on the single consumer thread — a
        # shared accumulator here would race across pack workers
        return buf, _tree_bytes(batch)

    total_bytes = 0
    t0 = time.perf_counter()
    if workers > 0:
        results = parallel_pack(_shape_set_plan(graphs, shape_set),
                                pack_job, workers=workers)
    else:
        results = map(pack_job, _shape_set_plan(graphs, shape_set))
    for buf, nbytes in results:
        total_bytes += nbytes
        if buf is not None:
            pool.release(*buf)
    return time.perf_counter() - t0, total_bytes


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import numpy as np

    from cgnn_tpu.data.compact import CompactSpec
    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.serve.shapes import plan_shape_set
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.infer import run_fast_inference
    from cgnn_tpu.train.step import make_predict_step

    m = args.dense_m
    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=m)
    graphs = load_synthetic_mp(args.n, cfg, seed=args.seed,
                               keep_geometry=args.wire != "featurized")
    spec = CompactSpec.build(graphs, cfg.gdf(), dense_m=m)
    ladder = plan_shape_set(graphs, args.batch_size, rungs=args.rungs,
                            dense_m=m, compact=spec)
    ladder_full = plan_shape_set(graphs, args.batch_size, rungs=args.rungs,
                                 dense_m=m)

    # tiny model: the metric is ingest, not FLOPs
    model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=32,
                                dense_m=m)
    nc, ec = capacities_for(graphs, args.batch_size, dense_m=m, snug=True)
    example = next(batch_iterator(graphs, args.batch_size, nc, ec,
                                  dense_m=m, in_cap=0, snug=True))
    state = create_train_state(
        model, example, make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(args.seed),
    )

    from cgnn_tpu.data.compact import make_expander

    pstep = jax.jit(make_predict_step(make_expander(spec)))

    # pack-only rates (no device in the loop)
    pack_s, bytes_staged = min(
        (_pack_all(graphs, ladder, args.workers) for _ in
         range(args.repeats)), key=lambda r: r[0],
    )
    serial_pack_s, serial_bytes = min(
        (_pack_all(graphs, ladder_full, 0) for _ in range(args.repeats)),
        key=lambda r: r[0],
    )

    # end-to-end rates, post-compile
    kw = dict(shape_set=ladder, predict_step=pstep,
              pack_workers=args.workers)
    preds, _ = run_fast_inference(state, graphs, args.batch_size, **kw)
    e2e = max(run_fast_inference(state, graphs, args.batch_size, **kw)[1]
              for _ in range(args.repeats))
    skw = dict(shape_set=ladder_full, predict_step=pstep, pack_workers=0)
    serial_preds, _ = run_fast_inference(state, graphs, args.batch_size,
                                         **skw)
    serial_e2e = max(
        run_fast_inference(state, graphs, args.batch_size, **skw)[1]
        for _ in range(args.repeats)
    )
    # the two staging modes must agree (compact expansion <= 1 ulp f32 on
    # edge features); a mismatch is a correctness bug, not a perf number
    np.testing.assert_allclose(preds, serial_preds, rtol=1e-4, atol=1e-4)

    # dispatch-side guard (ISSUE 5): the device-parallel e2e leg — same
    # ladder/step, round-robined over the device set. Bit-exact vs the
    # single-device run over identical batches, or the guard fails.
    from cgnn_tpu.serve.devices import resolve_devices

    devices = resolve_devices(args.devices)
    mkw = dict(kw, devices=devices)
    mdev_preds, _ = run_fast_inference(state, graphs, args.batch_size,
                                       **mkw)
    mdev_e2e = max(
        run_fast_inference(state, graphs, args.batch_size, **mkw)[1]
        for _ in range(args.repeats)
    )
    np.testing.assert_array_equal(preds, mdev_preds)

    out = {
        "pack_structs_per_sec": round(args.n / pack_s, 1),
        "e2e_structs_per_sec": round(e2e, 1),
        "e2e_multidev_structs_per_sec": round(mdev_e2e, 1),
        "inference_devices": len(devices),
        "bytes_staged": int(bytes_staged),
        "serial_pack_structs_per_sec": round(args.n / serial_pack_s, 1),
        "serial_e2e_structs_per_sec": round(serial_e2e, 1),
        "serial_bytes_staged": int(serial_bytes),
        "staged_bytes_ratio": round(serial_bytes / max(bytes_staged, 1), 2),
        "n": args.n,
        "workers": args.workers,
        "compact": True,
    }

    if args.wire != "featurized":
        # ---- ISSUE-11 raw-wire leg: bytes-on-wire + host-ms/request
        # for raw vs compact vs full, parity-asserted ----
        from cgnn_tpu.data.rawbatch import plan_raw_spec, raw_from_graph
        from cgnn_tpu.serve.shapes import plan_shape_set as _plan
        from cgnn_tpu.train.infer import run_raw_inference
        from cgnn_tpu.train.step import make_predict_step as _mps

        raw_spec = plan_raw_spec(graphs, cfg.gdf(), cfg.radius, m)
        raw_ladder = _plan(graphs, args.batch_size, rungs=args.rungs,
                           dense_m=m, compact=spec, raw=raw_spec)
        all_raws = [raw_from_graph(g) for g in graphs]
        # coverage-quantile caps (plan_raw_spec): the tail beyond them
        # rides the featurized path by design — report the admit share
        admit = [i for i, r in enumerate(all_raws)
                 if r is not None and raw_ladder.admits_raw(r)]
        assert len(admit) >= 0.8 * args.n, (
            f"only {len(admit)}/{args.n} of the calibration set fits "
            f"its own calibrated caps {raw_spec.to_meta()}"
        )
        raws = [all_raws[i] for i in admit]
        n_raw = len(raws)
        # bytes ON THE WIRE per request: the f32 raw encoding vs the
        # featurized arrays a legacy client ships (the acceptance
        # criterion is the ratio, >= 20x)
        wire_raw = sum(r.wire_nbytes for r in raws)
        wire_feat = sum(
            g.atom_fea.nbytes + g.edge_fea.nbytes + g.centers.nbytes
            + g.neighbors.nbytes for g in (graphs[i] for i in admit)
        )
        # host work per request: pack time only — the raw pack is slot
        # copies, the search itself runs in-program
        def _time_pack(fn):
            best = float("inf")
            for _ in range(args.repeats):
                t0 = time.perf_counter()
                fn()
                best = min(best, time.perf_counter() - t0)
            return best

        def _pack_raw_all():
            big = raw_ladder.largest
            for s0 in range(0, n_raw, big.graph_cap):
                raw_ladder.pack_raw(raws[s0:s0 + big.graph_cap],
                                    shape=big)

        raw_pack_s = _time_pack(_pack_raw_all)
        rstep = jax.jit(_mps(raw_ladder.expander(),
                             raw_ladder.raw_expander()))
        raw_preds, _ = run_raw_inference(state, raws, raw_ladder,
                                         predict_step=rstep)
        raw_e2e = max(
            run_raw_inference(state, raws, raw_ladder,
                              predict_step=rstep)[1]
            for _ in range(args.repeats)
        )
        # parity: the in-program graph construction must agree with the
        # host featurizer's predictions (f32-roundoff tolerance — the
        # search runs in f32 where the host ran f64; tests pin the
        # bit-exact structural contract)
        feat_preds, _ = run_fast_inference(
            state, [graphs[i] for i in admit], args.batch_size,
            shape_set=raw_ladder, predict_step=rstep, pack_workers=0,
        )
        np.testing.assert_allclose(raw_preds, feat_preds, rtol=1e-3,
                                   atol=1e-3)
        out.update({
            "raw_e2e_structs_per_sec": round(raw_e2e, 1),
            "raw_pack_structs_per_sec": round(n_raw / raw_pack_s, 1),
            "raw_admit_share": round(len(admit) / args.n, 3),
            "wire_bytes_raw": int(wire_raw),
            "wire_bytes_featurized": int(wire_feat),
            "wire_bytes_ratio": round(wire_feat / max(wire_raw, 1), 1),
            "host_ms_per_request_raw": round(raw_pack_s / n_raw * 1e3,
                                             4),
            "host_ms_per_request_compact": round(pack_s / args.n * 1e3,
                                                 4),
            "host_ms_per_request_full": round(
                serial_pack_s / args.n * 1e3, 4),
        })

    print(json.dumps(jsonfinite(out)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
