#!/usr/bin/env bash
# Multi-host dryrun (ISSUE 10; tier1.yml multihost-dryrun job):
# 2 jax.distributed processes on the CPU backend (gloo cross-process
# collectives), one local device each -> a global 2-device ('data',)
# mesh. Proves the three multi-host invariants in-container:
#
#  1. TRAIN: both processes run `train.py --data-parallel` over the
#     global mesh with per-host strided data shards; grads/metrics are
#     pmean/psum-ed across hosts, so the per-epoch loss lines must be
#     IDENTICAL on both processes.
#  2. SINGLE COMMITTER: process 0 alone commits checkpoints into the
#     shared directory; process 1 logs the skip and writes nothing.
#  3. COORDINATED HOT RELOAD: both processes lockstep-poll the shared
#     checkpoint dir (dist.ReloadCoordinator); process 0 commits a new
#     save mid-run; both processes must swap to the SAME version at the
#     SAME poll round, after the shared barrier.
#
# Runs anywhere jax[cpu] does (synthetic data; ~2 min).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT
PORT="${MULTIHOST_SMOKE_PORT:-18621}"

run2() {  # run2 LOGPREFIX CMD... -> same command on process 0 and 1
  local prefix=$1; shift
  local pids=()
  for p in 0 1; do
    CGNN_TPU_COORDINATOR="127.0.0.1:$PORT" \
    CGNN_TPU_NUM_PROCESSES=2 \
    CGNN_TPU_PROCESS_ID=$p \
      "$@" > "$WORK/${prefix}_$p.log" 2>&1 &
    pids[$p]=$!
  done
  local rc=0
  for p in 0 1; do
    if ! wait "${pids[$p]}"; then
      echo "process $p of '$prefix' failed:" >&2
      tail -40 "$WORK/${prefix}_$p.log" >&2
      rc=1
    fi
  done
  return $rc
}

echo "== leg 1: 2-process DP training (identical loss, one committer) =="
run2 train timeout 600 python train.py --synthetic 96 --epochs 2 -b 8 \
  --device cpu --data-parallel --telemetry off --no-preempt-handler \
  --guard off --ckpt-dir "$WORK/ckpt" --compile-cache ''

# identical per-epoch loss on both processes (grads and metric sums are
# allreduced over the global mesh, so the trajectories ARE one model);
# the trailing wall-clock "(Xs)" is per-host noise — strip it
grep "^Epoch " "$WORK/train_0.log" | sed 's/ *([0-9.]*s)$//' > "$WORK/epochs_0.txt"
grep "^Epoch " "$WORK/train_1.log" | sed 's/ *([0-9.]*s)$//' > "$WORK/epochs_1.txt"
test -s "$WORK/epochs_0.txt"
if ! diff -u "$WORK/epochs_0.txt" "$WORK/epochs_1.txt"; then
  echo "FAIL: per-epoch losses diverged across hosts" >&2
  exit 1
fi
echo "leg 1 loss lines identical:"
cat "$WORK/epochs_0.txt"

# process 0 alone commits: proc 1 logged the skip and the directory
# holds committed saves (manifest = commit marker)
grep -q "skips checkpoint commits" "$WORK/train_1.log"
if grep -q "skips checkpoint commits" "$WORK/train_0.log"; then
  echo "FAIL: process 0 skipped commits (nobody committed?)" >&2
  exit 1
fi
ls -d "$WORK"/ckpt/ckpt-*/ >/dev/null
python - "$WORK/ckpt" <<'EOF'
import sys
sys.path.insert(0, ".")
from cgnn_tpu.train.checkpoint import CheckpointManager
mgr = CheckpointManager(sys.argv[1])
newest = mgr.newest_committed()
assert newest is not None, "no committed save in the shared dir"
print("leg 1 single-committer ok: newest committed save", newest)
EOF

echo "== leg 2: cross-host coordinated hot reload =="
PORT=$((PORT + 1))
run2 reload timeout 300 python scripts/multihost_reload_probe.py "$WORK/ckpt"

R0=$(grep "^RELOAD_RESULT" "$WORK/reload_0.log")
R1=$(grep "^RELOAD_RESULT" "$WORK/reload_1.log")
echo "proc 0: $R0"
echo "proc 1: $R1"
if [ "$R0" != "$R1" ]; then
  echo "FAIL: hot reload landed differently across hosts" >&2
  exit 1
fi
# the swap must have MOVED the version (not re-served the original)
case "$R0" in
  *version=ckpt-*) : ;;
  *) echo "FAIL: unexpected reload result: $R0" >&2; exit 1 ;;
esac

echo "multihost smoke: ALL LEGS PASSED"
