#!/usr/bin/env python
"""Performance model of the flagship train step (VERDICT r2 item #1).

Answers, with measurements on the real chip:
1. How much of the per-step wall time is tunnel/dispatch overhead vs
   device execution?  (per-step dispatch loop vs whole-`lax.scan` dispatch
   of the SAME steps — identical math, one host round trip.)
2. Where does device time go?  (jax.profiler trace of the scanned steps,
   parsed into a top-op table.)
3. Where does the step sit on the v5e roofline?  (analytic bytes-moved and
   matmul FLOPs vs ~819 GB/s HBM and 197 bf16 TFLOP/s.)

Writes PERF_DATA.json with everything; PERF.md (committed) interprets it.

Usage: python scripts/profile_step.py [--trace-dir /tmp/cgnn_trace]
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def build_workload(dense_m=12):
    """The bench.py PRIMARY workload: MP-like distribution, dense layout,
    snug packing, bf16 edge storage (kept in lockstep with bench.py)."""
    import jax
    import numpy as np

    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
    from cgnn_tpu.data.graph import PaddingStats, bucketed_batch_iterator

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = load_synthetic_mp(8192, cfg, seed=0)
    stats = PaddingStats()
    batches = list(
        bucketed_batch_iterator(
            graphs, 512, 3, stats=stats,
            rng=np.random.default_rng(0), dense_m=dense_m, snug=True,
            edge_dtype=jax.numpy.bfloat16,
        )
    )
    return graphs, batches, stats


def build_state(batches, dense_m=12):
    import jax
    import numpy as np

    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.step import make_train_step

    model = CrystalGraphConvNet(
        atom_fea_len=64, n_conv=3, h_fea_len=128,
        dtype=jax.numpy.bfloat16, dense_m=dense_m,
    )
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10_000])
    targets = np.concatenate(
        [np.asarray(b.targets)[np.asarray(b.graph_mask) > 0] for b in batches]
    )
    normalizer = Normalizer.fit(targets)
    state = create_train_state(model, batches[0], tx, normalizer)
    return state, jax.jit(make_train_step(), donate_argnums=0)


def measure_dispatch_loop(state, step, device_batches, real_per_batch, n=60):
    """Per-step dispatch (bench.py round-2 mode): host dispatches every step."""
    import jax  # noqa: F401

    structures = 0.0
    t0 = time.perf_counter()
    metrics = None
    for i in range(n):
        k = i % len(device_batches)
        state, metrics = step(state, device_batches[k])
        structures += real_per_batch[k]
    float(metrics["loss_sum"])  # value-fetch fence
    dt = time.perf_counter() - t0
    return state, structures / dt, dt / n


def measure_scan_dispatch(state, raw_step, device_batches, real_per_batch,
                          steps_per_scan=32, n_scans=3):
    """Whole-chunk dispatch: `steps_per_scan` steps per host round trip."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cgnn_tpu.data.graph import batch_shape_key

    # group identically-shaped batches and stack on a leading axis
    groups, reals = {}, {}
    for b, r in zip(device_batches, real_per_batch):
        key = batch_shape_key(b)
        groups.setdefault(key, []).append(b)
        reals.setdefault(key, []).append(r)
    stacked = {
        k: jax.device_put(jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *bs))
        for k, bs in groups.items()
    }

    def scan_fn(state, st, perm):
        def body(carry, i):
            batch = jax.tree_util.tree_map(lambda x: x[i], st)
            carry, metrics = raw_step(carry, batch)
            return carry, metrics["loss_sum"]

        state2, losses = jax.lax.scan(body, state, perm)
        return state2, losses.sum()

    scan_jit = jax.jit(scan_fn, donate_argnums=(0,))

    # warmup-compile each group's scan
    perms = {}
    for k, st in stacked.items():
        n_b = len(groups[k])
        idx = np.arange(steps_per_scan) % n_b
        perms[k] = jnp.asarray(idx)
        state, s = scan_jit(state, st, perms[k])
    float(s)

    per_scan_structs = {
        k: float(np.sum([reals[k][i % len(reals[k])]
                         for i in range(steps_per_scan)]))
        for k in stacked
    }
    t0 = time.perf_counter()
    total_structs = 0.0
    for _ in range(n_scans):
        for k, st in stacked.items():
            state, s = scan_jit(state, st, perms[k])
            total_structs += per_scan_structs[k]
    float(s)
    dt = time.perf_counter() - t0
    n_steps = n_scans * len(stacked) * steps_per_scan
    return state, scan_jit, stacked, perms, total_structs / dt, dt / n_steps


def trace_and_parse(scan_jit, state, stacked, perms, trace_dir):
    """Trace one scanned chunk per shape; aggregate device op time."""
    import jax

    jax.profiler.start_trace(trace_dir)
    for k, st in stacked.items():
        state, s = scan_jit(state, st, perms[k])
    float(s)
    jax.profiler.stop_trace()

    events = []
    for path in glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    ):
        with gzip.open(path, "rt") as f:
            trace = json.load(f)
        events.extend(trace.get("traceEvents", []))
    # device lanes: pid metadata names like "/device:TPU:0 ..." or "TPU"-ish
    pid_names = {
        e["pid"]: e["args"].get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and "args" in e
    }
    device_pids = {
        p for p, n in pid_names.items()
        if "TPU" in n or "tpu" in n or "device" in n.lower()
    }
    op_time: dict[str, float] = {}
    total = 0.0
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in device_pids:
            name = e.get("name", "?")
            dur = float(e.get("dur", 0.0))  # microseconds
            op_time[name] = op_time.get(name, 0.0) + dur
            total += dur
    top = sorted(op_time.items(), key=lambda kv: -kv[1])[:25]
    return {
        "pid_names": {str(k): v for k, v in pid_names.items()},
        "device_total_us": total,
        "top_ops_us": top,
    }


def analytic_roofline(batches, f=64, h=128, n_conv=3, n_h=1):
    """Bytes moved + matmul FLOPs per average step (bf16 compute).

    Bytes: every major [E|N, *] tensor read/written once per use in
    fwd+bwd (lower bound — XLA fusion means some never hit HBM; padding
    slots DO move, so use slot counts, not real counts).
    """
    import numpy as np

    n_slots = float(np.mean([b.node_capacity for b in batches]))
    e_slots = float(np.mean([b.edge_capacity for b in batches]))
    n_real = float(np.mean([np.asarray(b.node_mask).sum() for b in batches]))
    e_real = float(np.mean([np.asarray(b.edge_mask).sum() for b in batches]))
    g = float(np.mean([np.asarray(b.graph_mask).sum() for b in batches]))
    in_cap = float(np.mean(
        [b.in_mask.shape[1] for b in batches if b.in_mask is not None]
    )) if batches[0].in_slots is not None else 0.0
    # [-1]: dense batches store edges [N, M, G]; [E, G] for COO
    gauss = batches[0].edges.shape[-1]
    bf2 = 2.0  # bf16 bytes

    # Forward per conv layer, slot counts (padding moves too):
    #  read nodes[N,F] (gather, twice: v_i bcast + v_j), write z[E,2F+G] ->
    #  matmul -> z2[E,2F] (rw), BN (rw), msg[E,2F->F], agg[N,F], out[N,F]
    per_conv_fwd = (
        2 * n_slots * f * bf2          # node reads (v_i, v_j sources)
        + e_slots * (2 * f + gauss) * bf2   # z write (concat)
        + e_slots * (2 * f + gauss) * bf2   # z read by matmul
        + 2 * e_slots * 2 * f * bf2    # z2 write + read (BN+gate)
        + e_slots * f * bf2            # msg write
        + 2 * n_slots * f * bf2        # agg + out
    )
    # Backward roughly doubles the edge-side traffic and adds the
    # transpose-gather reduce: ct[E,F] read + in_slots[N,In] idx (4B) +
    # contrib reduce [N,In,F]
    per_conv_bwd = per_conv_fwd + n_slots * in_cap * (f * bf2 + 4)
    embed = 2 * n_slots * (92 + f) * bf2
    head = 2 * g * (f + h) * bf2 * 2
    bytes_step = embed + n_conv * (per_conv_fwd + per_conv_bwd) + head

    flops = 3.0 * (
        2.0 * n_real * 92 * f
        + n_conv * 2.0 * e_real * (2 * f + gauss) * (2 * f)
        + 2.0 * g * f * h
        + (n_h - 1) * 2.0 * g * h * h
        + 2.0 * g * h
    )
    # padded-slot matmul FLOPs actually executed (MXU does padding too)
    flops_slots = 3.0 * (
        2.0 * n_slots * 92 * f
        + n_conv * 2.0 * e_slots * (2 * f + gauss) * (2 * f)
        + 2.0 * g * f * h
        + 2.0 * g * h
    )
    return {
        "avg_node_slots": n_slots, "avg_edge_slots": e_slots,
        "avg_real_nodes": n_real, "avg_real_edges": e_real,
        "avg_real_graphs": g, "in_cap": in_cap,
        "bytes_per_step_est": bytes_step,
        "useful_matmul_flops_per_step": flops,
        "executed_matmul_flops_per_step": flops_slots,
        "hbm_peak_gbps": 819.0,
        "bf16_peak_tflops": 197.0,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace-dir", default="/tmp/cgnn_trace")
    ap.add_argument("--steps-per-scan", type=int, default=32)
    ap.add_argument("--out", default="PERF_DATA.json")
    args = ap.parse_args()

    import jax
    import numpy as np

    from cgnn_tpu.train.step import make_train_step

    print(f"devices: {jax.devices()}", file=sys.stderr)
    graphs, batches, stats = build_workload()
    print(f"built {len(batches)} batches, {stats.summary()}", file=sys.stderr)
    state, step = build_state(batches)
    device_batches = [jax.device_put(b) for b in batches]
    real_per_batch = [float(np.asarray(b.graph_mask).sum()) for b in batches]

    # compile every shape once (per-step path)
    seen = set()
    metrics = None
    for b in device_batches:
        from cgnn_tpu.data.graph import batch_shape_key

        key = batch_shape_key(b)
        if key not in seen:
            seen.add(key)
            state, metrics = step(state, b)
    float(metrics["loss_sum"])
    print("per-step path compiled", file=sys.stderr)

    state, rate_loop, per_step_loop = measure_dispatch_loop(
        state, step, device_batches, real_per_batch
    )
    print(f"dispatch-loop: {rate_loop:,.0f} structs/s "
          f"({per_step_loop*1e3:.2f} ms/step)", file=sys.stderr)

    raw_step = make_train_step()
    state, scan_jit, stacked, perms, rate_scan, per_step_scan = (
        measure_scan_dispatch(
            state, raw_step, device_batches, real_per_batch,
            steps_per_scan=args.steps_per_scan,
        )
    )
    print(f"scan-dispatch: {rate_scan:,.0f} structs/s "
          f"({per_step_scan*1e3:.2f} ms/step)", file=sys.stderr)

    trace = trace_and_parse(scan_jit, state, stacked, perms, args.trace_dir)
    print(f"trace: device total {trace['device_total_us']/1e3:.1f} ms",
          file=sys.stderr)

    roof = analytic_roofline(batches)
    avg_structs = float(np.mean(real_per_batch))
    dev_step_s = per_step_scan  # scan mode ~= device-bound step time
    result = {
        "workload": "MP-like lognormal, batch 512, 3 buckets, dense_m=12",
        "dispatch_loop": {
            "structs_per_sec": rate_loop, "ms_per_step": per_step_loop * 1e3,
        },
        "scan_dispatch": {
            "structs_per_sec": rate_scan, "ms_per_step": per_step_scan * 1e3,
            "steps_per_scan": args.steps_per_scan,
        },
        "dispatch_overhead_ms_per_step": (per_step_loop - per_step_scan) * 1e3,
        "roofline": {
            **roof,
            "achieved_gbps_scan": roof["bytes_per_step_est"] / dev_step_s / 1e9,
            "achieved_useful_tflops_scan":
                roof["useful_matmul_flops_per_step"] / dev_step_s / 1e12,
            "achieved_executed_tflops_scan":
                roof["executed_matmul_flops_per_step"] / dev_step_s / 1e12,
            "mfu_scan": roof["useful_matmul_flops_per_step"] / dev_step_s
                        / (roof["bf16_peak_tflops"] * 1e12),
            "bandwidth_bound_step_ms":
                roof["bytes_per_step_est"] / (819e9) * 1e3,
            "compute_bound_step_ms":
                roof["executed_matmul_flops_per_step"] / (197e12) * 1e3,
        },
        "avg_structs_per_batch": avg_structs,
        "trace": trace,
    }
    with open(args.out, "w") as fo:
        json.dump(jsonfinite(result), fo, indent=1)
    print(json.dumps(jsonfinite({k: v for k, v in result.items()
                              if k != "trace"}), indent=1))


if __name__ == "__main__":
    main()
