#!/usr/bin/env python
"""Classification parity harness: JAX vs the in-tree torch CGCNN oracle.

VERDICT r3 next-step #8: regression has a measured MAE-parity acceptance
(MAE_PARITY_MP.json); classification (reference ``task=classification``,
SURVEY.md §2 component 1) had only unit tests. This trains both frameworks
on the same synthetic metal/insulator-style task — MP-like structures,
binary label = formation-energy proxy above/below the dataset median —
with the same hyperparameters and matched init draws, over >= 3 seeds, and
compares accuracy and AUC.

Prints one JSON line:
  {"torch_accuracy", "jax_accuracy", "accuracy_ratio", "torch_auc",
   "jax_auc", ...}
Exit 1 if jax accuracy is more than --tolerance below the oracle's.

Usage: python scripts/class_parity.py [--n 1024] [--epochs 40] [--repeats 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def binary_labeled_dataset(n: int, seed: int):
    """MP-like structures with label = target above/below the median.

    The median threshold makes the classes balanced by construction; the
    label is a deterministic function of structure (no label noise), so
    both frameworks face the same learnable decision boundary.
    """
    import numpy as np

    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp

    cfg = FeaturizeConfig(radius=4.5, max_num_nbr=12)
    graphs = load_synthetic_mp(n, cfg, seed=seed)
    median = float(np.median([g.target[0] for g in graphs]))
    for g in graphs:
        g.target = np.array([1.0 if g.target[0] > median else 0.0],
                            np.float32)
    return graphs, cfg


def torch_train_eval(split, *, epochs, batch_size, lr, seed, max_num_nbr):
    """Train the classification oracle -> (test accuracy, test AUC)."""
    import numpy as np
    import torch

    from cgnn_tpu.data.graph import dense_neighbor_views
    from cgnn_tpu.train.metrics import class_eval
    from tests.oracle.torch_cgcnn import TorchCGCNN

    train_g, val_g, test_g = split
    m = max_num_nbr

    def dense_views(g):
        cached = getattr(g, "_dense_views", None)
        if cached is None:
            cached = g._dense_views = dense_neighbor_views(g, m)
        return cached

    def collate(batch_graphs):
        atom, nbr, idx, masks, ranges, ys = [], [], [], [], [], []
        off = 0
        for g in batch_graphs:
            n = g.num_nodes
            dn, di, dm = dense_views(g)
            atom.append(np.asarray(g.atom_fea, np.float32))
            nbr.append(dn)
            idx.append(di + off)
            masks.append(dm)
            ranges.append(torch.arange(off, off + n))
            ys.append(int(g.target[0]))
            off += n
        return (
            torch.from_numpy(np.concatenate(atom)),
            torch.from_numpy(np.concatenate(nbr)),
            torch.from_numpy(np.concatenate(idx)).long(),
            torch.from_numpy(np.concatenate(masks)),
            ranges,
            torch.tensor(ys, dtype=torch.long),
        )

    torch.manual_seed(seed)
    model = TorchCGCNN(
        orig_atom_fea_len=train_g[0].atom_fea.shape[1],
        nbr_fea_len=train_g[0].edge_fea.shape[1],
        atom_fea_len=64, n_conv=3, h_fea_len=128, n_h=1,
        classification=True, num_classes=2,
    )
    opt = torch.optim.Adam(model.parameters(), lr=lr)
    shuffle_rng = np.random.default_rng(seed)

    def run(split_graphs, train=False):
        model.train(train)
        order = (shuffle_rng.permutation(len(split_graphs)) if train
                 else np.arange(len(split_graphs)))
        logps, labels = [], []
        for i in range(0, len(order), batch_size):
            bg = [split_graphs[j] for j in order[i:i + batch_size]]
            atom, nbr, idx, mask, ranges, y = collate(bg)
            out = model(atom, nbr, idx, ranges, nbr_mask=mask)
            if train:
                loss = torch.nn.functional.nll_loss(out, y)
                opt.zero_grad()
                loss.backward()
                opt.step()
            with torch.no_grad():
                logps.append(out.detach().numpy())
                labels.extend(int(v) for v in y)
        return class_eval(np.concatenate(logps), np.array(labels))

    best_val, best_state = -float("inf"), None
    for _epoch in range(epochs):
        run(train_g, train=True)
        with torch.no_grad():
            val = run(val_g)
        if val["accuracy"] > best_val:
            best_val = val["accuracy"]
            best_state = {k: v.clone() for k, v in model.state_dict().items()}
    model.load_state_dict(best_state)
    with torch.no_grad():
        return run(test_g), best_val


def jax_train_eval(split, *, epochs, batch_size, lr, seed,
                   matched_init=False):
    import numpy as np

    import jax

    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import fit
    from cgnn_tpu.train.metrics import class_eval
    from cgnn_tpu.train.step import make_predict_step

    train_g, val_g, test_g = split
    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                n_h=1, classification=True, num_classes=2)
    tx = make_optimizer(optim="adam", lr=lr, lr_milestones=[10**9])
    node_cap, edge_cap = capacities_for(train_g, batch_size)
    example = next(batch_iterator(train_g, batch_size, node_cap, edge_cap))
    state = create_train_state(
        model, example, tx, Normalizer.identity(1), rng=jax.random.key(seed)
    )
    if matched_init:
        import torch

        from tests.oracle.torch_cgcnn import TorchCGCNN, variables_from_torch

        torch.manual_seed(seed + 7919)
        fresh = TorchCGCNN(
            orig_atom_fea_len=train_g[0].atom_fea.shape[1],
            nbr_fea_len=train_g[0].edge_fea.shape[1],
            atom_fea_len=64, n_conv=3, h_fea_len=128, n_h=1,
            classification=True, num_classes=2,
        )
        variables = variables_from_torch(
            fresh, {"params": state.params, "batch_stats": state.batch_stats}
        )
        state = state.replace(
            params=jax.tree_util.tree_map(
                lambda x: np.asarray(x, np.float32), variables["params"]
            ),
            batch_stats=jax.tree_util.tree_map(
                lambda x: np.asarray(x, np.float32),
                variables["batch_stats"],
            ),
        )
    best = {"params": state.params, "batch_stats": state.batch_stats,
            "val": -float("inf")}

    def on_epoch_end(s, _epoch, val_m, is_best):
        if is_best:
            # true host SNAPSHOTS: on CPU, device_get returns views
            # ALIASING the device buffers, which the donated train step
            # mutates in later epochs (the PR-2 checkpoint-corruption
            # incident) — without the np.array copy, "best" params
            # silently drift toward the LAST epoch's values
            best.update(
                params=jax.tree_util.tree_map(
                    np.array, jax.device_get(s.params)),
                batch_stats=jax.tree_util.tree_map(
                    np.array, jax.device_get(s.batch_stats)),
                val=val_m["correct"])

    state, result = fit(
        state, train_g, val_g, epochs=epochs, batch_size=batch_size,
        node_cap=node_cap, edge_cap=edge_cap, classification=True,
        seed=seed, print_freq=0, on_epoch_end=on_epoch_end,
        log_fn=lambda *a, **k: None,
    )
    state = state.replace(params=best["params"],
                          batch_stats=best["batch_stats"])
    pstep = jax.jit(make_predict_step())
    logps, labels = [], []
    idx = 0
    for b in batch_iterator(test_g, batch_size, node_cap, edge_cap):
        out = np.array(jax.device_get(pstep(state, b)))  # copy: GC-ALIAS
        n_real = int(np.asarray(b.graph_mask).sum())
        logps.append(out[:n_real])
        labels.extend(int(test_g[idx + k].target[0]) for k in range(n_real))
        idx += n_real
    return class_eval(np.concatenate(logps), np.array(labels)), best["val"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--n", type=int, default=1024)
    p.add_argument("--epochs", type=int, default=40)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--device", choices=["auto", "cpu"], default="auto")
    p.add_argument("--tolerance", type=float, default=0.05,
                   help="max allowed (1 - jax_accuracy / torch_accuracy)")
    p.add_argument("--matched-init", action="store_true")
    args = p.parse_args(argv)
    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from cgnn_tpu.data.dataset import train_val_test_split

    graphs, cfg = binary_labeled_dataset(args.n, seed=11)
    runs = []
    t_torch = t_jax = 0.0
    for seed in range(args.seed, args.seed + args.repeats):
        split = train_val_test_split(graphs, 0.8, 0.1, seed=seed)
        t0 = time.perf_counter()
        torch_m, torch_val = torch_train_eval(
            split, epochs=args.epochs, batch_size=args.batch_size,
            lr=args.lr, seed=seed, max_num_nbr=cfg.max_num_nbr,
        )
        t_torch += time.perf_counter() - t0
        t0 = time.perf_counter()
        jax_m, jax_val = jax_train_eval(
            split, epochs=args.epochs, batch_size=args.batch_size,
            lr=args.lr, seed=seed, matched_init=args.matched_init,
        )
        t_jax += time.perf_counter() - t0
        runs.append({
            "seed": seed,
            "torch_accuracy": round(torch_m["accuracy"], 4),
            "jax_accuracy": round(jax_m["accuracy"], 4),
            "torch_auc": round(torch_m["auc"], 4),
            "jax_auc": round(jax_m["auc"], 4),
            "torch_val_acc": round(torch_val, 4),
            "jax_val_acc": round(jax_val, 4),
        })

    mean = lambda k: float(np.mean([r[k] for r in runs]))  # noqa: E731
    acc_t, acc_j = mean("torch_accuracy"), mean("jax_accuracy")
    print(json.dumps(jsonfinite({
        "metric": "classification_parity",
        "matched_init": bool(args.matched_init),
        "torch_accuracy": round(acc_t, 4),
        "jax_accuracy": round(acc_j, 4),
        "accuracy_ratio": round(acc_j / acc_t, 4),
        "torch_auc": round(mean("torch_auc"), 4),
        "jax_auc": round(mean("jax_auc"), 4),
        "repeats": args.repeats,
        "runs": runs,
        "n_structures": args.n,
        "epochs": args.epochs,
        "torch_train_s": round(t_torch, 1),
        "jax_train_s": round(t_jax, 1),
    })))
    return 0 if acc_j / acc_t >= 1.0 - args.tolerance else 1


if __name__ == "__main__":
    sys.exit(main())
