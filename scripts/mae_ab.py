#!/usr/bin/env python
"""Controlled accuracy A/B: reconcile the flagship-scale val-MAE record.

VERDICT r3 weak #1: the MP-146k scale proof recorded val MAE 0.043 in round
2 but 0.05988 with the round-3 stack, and nothing on the record attributes
the delta. This script isolates the r2->r3 stack changes one at a time on a
deterministic subset of the same cached MP-like dataset, same seed, same
epoch budget, ALL CONFIGS IN ONE PROCESS (the repo's honest-bench practice —
tunnel phase drift cannot skew a same-process comparison, and MAE is
phase-independent anyway):

  r4         dense two-tier + snug + scan + bf16 + one-pass BN (current)
  perstep    r4 with the per-step device-resident loop (no scan)
  ladder     r4 with ladder packing (r2's batch-size-closed batches)
  twopass    r4 with two-pass centered BN statistics (r2 estimator)
  f32        r4 with float32 model compute
  r2stack    COO + ladder + per-step + two-pass BN + bf16 (the r2 recipe)
  r4-s1/-s2  r4 at seeds 1, 2 (seed-noise band, split resampled too)

Each record carries steps/epoch (packing policies change the optimizer step
count at fixed epochs — the leading undertraining suspect) and the full
per-epoch val-MAE curve. Writes MAE_AB.json.

Usage: python scripts/mae_ab.py [--n 40960] [--epochs 6]
       [--cache /tmp/mp146k_cache.npz]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def run_config(
    name: str,
    graphs,
    *,
    epochs: int,
    batch_size: int,
    buckets: int,
    seed: int,
    dense: bool,
    snug: bool,
    scan: bool,
    two_pass: bool,
    dtype_name: str,
    max_num_nbr: int,
) -> dict:
    import jax
    import numpy as np

    from cgnn_tpu.data.dataset import train_val_test_split
    from cgnn_tpu.data.graph import bucketed_batch_iterator, pack_graphs
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.ops.norm import force_two_pass_stats
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import capacities_for, fit

    t0 = time.perf_counter()
    train_g, val_g, _ = train_val_test_split(graphs, 0.9, 0.05, seed=seed)
    layout_m = max_num_nbr if dense else None
    dtype = jax.numpy.bfloat16 if dtype_name == "bf16" else jax.numpy.float32
    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                dtype=dtype, dense_m=layout_m)
    tx = make_optimizer(optim="adam", lr=0.01, lr_milestones=[10**9])
    normalizer = Normalizer.fit(np.stack([g.target for g in train_g]))
    node_cap, edge_cap = capacities_for(train_g, batch_size,
                                        dense_m=layout_m, snug=snug)
    example = pack_graphs(
        sorted(train_g[: batch_size // 2], key=lambda g: g.num_nodes),
        node_cap, edge_cap, batch_size, dense_m=layout_m,
    )
    state = create_train_state(model, example, tx, normalizer,
                               rng=jax.random.key(seed))

    # the step count this packing policy yields (undertraining suspect):
    # materialize one epoch's iterator exactly as fit() will
    steps = sum(1 for _ in bucketed_batch_iterator(
        train_g, batch_size, buckets,
        shuffle=True, rng=np.random.default_rng(seed),
        dense_m=layout_m, snug=snug,
    ))

    curve: list[float] = []
    train_curve: list[float] = []

    def on_epoch_metrics(_e, train_m, val_m):
        curve.append(round(float(val_m.get("mae", np.nan)), 5))
        train_curve.append(round(float(train_m.get("mae", np.nan)), 5))

    force_two_pass_stats(two_pass)
    try:
        state, result = fit(
            state, train_g, val_g, epochs=epochs, batch_size=batch_size,
            node_cap=node_cap, edge_cap=edge_cap, buckets=buckets,
            seed=seed, print_freq=0, snug=snug, dense_m=layout_m,
            scan_epochs=scan, device_resident=True,
            on_epoch_metrics=on_epoch_metrics,
            log_fn=lambda m: print(f"  [{name}] {m}", file=sys.stderr),
        )
    finally:
        force_two_pass_stats(False)
    rec = {
        "name": name,
        "seed": seed,
        "dense": dense,
        "snug": snug,
        "scan": scan,
        "two_pass_bn": two_pass,
        "dtype": dtype_name,
        "steps_per_epoch": steps,
        "val_mae_per_epoch": curve,
        "train_mae_per_epoch": train_curve,
        "best_val_mae": round(float(result["best"]), 5),
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    print(json.dumps(jsonfinite(rec)), file=sys.stderr)
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--n", type=int, default=40_960)
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--buckets", type=int, default=3)
    p.add_argument("--cache", type=str, default="/tmp/mp146k_cache.npz")
    p.add_argument("--out", type=str, default="MAE_AB.json")
    p.add_argument("--configs", type=str, default="",
                   help="comma-separated subset of config names to run")
    args = p.parse_args(argv)

    from cgnn_tpu.data.cache import load_graph_cache
    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    if os.path.exists(args.cache):
        t0 = time.perf_counter()
        graphs = load_graph_cache(args.cache)[: args.n]
        print(f"loaded {len(graphs)} graphs from cache "
              f"({time.perf_counter() - t0:.0f}s)", file=sys.stderr)
    else:
        print(f"cache {args.cache} missing; featurizing {args.n} "
              f"(slow, one-time)", file=sys.stderr)
        graphs = load_synthetic_mp(args.n, cfg, seed=0)

    base = dict(
        epochs=args.epochs, batch_size=args.batch_size, buckets=args.buckets,
        seed=0, dense=True, snug=True, scan=True, two_pass=False,
        dtype_name="bf16", max_num_nbr=cfg.max_num_nbr,
    )
    matrix = [
        ("r4", {}),
        ("perstep", {"scan": False}),
        ("ladder", {"snug": False}),
        ("twopass", {"two_pass": True}),
        ("f32", {"dtype_name": "f32"}),
        ("r2stack", {"dense": False, "snug": False, "scan": False,
                     "two_pass": True}),
        ("r4-s1", {"seed": 1}),
        ("r4-s2", {"seed": 2}),
    ]
    only = {s.strip() for s in args.configs.split(",") if s.strip()}
    records = []
    for name, overrides in matrix:
        if only and name not in only:
            continue
        print(f"=== {name} ===", file=sys.stderr)
        records.append(run_config(name, graphs, **(base | overrides)))

    out = {
        "metric": "mae_ab",
        "n_structures": len(graphs),
        "epochs": args.epochs,
        "records": records,
    }
    with open(args.out, "w") as f:
        json.dump(jsonfinite(out), f, indent=2)
    print(json.dumps(jsonfinite(
        {r["name"]: r["best_val_mae"] for r in records})))
    return 0


if __name__ == "__main__":
    sys.exit(main())
