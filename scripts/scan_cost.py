#!/usr/bin/env python
"""ScanEpochDriver fixed-cost breakdown at bench scale (VERDICT r3 #5).

Round 3 measured 31.5k structs/s through the production epoch driver at
18-batch bench epochs vs ~50k through the steady-step loop, then removed
the epoch mode from bench.py instead of explaining the gap. This script
measures WHERE the gap goes, on the exact bench workload (8192 MP-like
structures, batch 512, 3 buckets, snug, dense, bf16):

  1. steady-step rate: the bench.py dispatch loop (reference ceiling)
  2. scan-epoch rate: ScanEpochDriver train epochs, post-compile
  3. the driver's per-phase wall accounting (ScanEpochDriver.timings):
     chunk-schedule build, chunk dispatches, mixed-tail dispatches
     (single-step scans — the BN-EMA mixing tail), the deferred fetch

Prints one JSON line; commit as SCAN_COST.json next to PERF.md.

Usage: python scripts/scan_cost.py [--n 8192] [--epochs 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--n", type=int, default=8192)
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--buckets", type=int, default=3)
    p.add_argument("--fused-epilogue", choices=["off", "xla", "pallas"],
                   default="off")
    p.add_argument("--out", type=str, default="")
    args = p.parse_args(argv)

    import jax
    import numpy as np

    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
    from cgnn_tpu.data.graph import PaddingStats, bucketed_batch_iterator
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import ScanEpochDriver
    from cgnn_tpu.train.step import make_eval_step, make_train_step

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = load_synthetic_mp(args.n, cfg, seed=0)
    rng = np.random.default_rng(0)
    stats = PaddingStats()
    batches = list(bucketed_batch_iterator(
        graphs, args.batch_size, args.buckets, shuffle=True, rng=rng,
        stats=stats, dense_m=cfg.max_num_nbr, snug=True,
        edge_dtype=jax.numpy.bfloat16,
    ))
    structs = sum(float(np.asarray(b.graph_mask).sum()) for b in batches)
    model = CrystalGraphConvNet(
        atom_fea_len=64, n_conv=3, h_fea_len=128, dtype=jax.numpy.bfloat16,
        dense_m=cfg.max_num_nbr,
        fused_epilogue=None if args.fused_epilogue == "off"
        else args.fused_epilogue,
    )
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10**9])
    normalizer = Normalizer.fit(np.stack([g.target for g in graphs]))
    state = create_train_state(model, batches[0], tx, normalizer)

    out: dict = {
        "metric": "scan_epoch_cost_breakdown",
        "n_structures": args.n,
        "batches_per_epoch": len(batches),
        "structs_per_epoch": structs,
        "fused_epilogue": args.fused_epilogue,
    }

    # 1. steady-step ceiling (bench.py loop, value-fetch fenced)
    train_step = jax.jit(make_train_step(), donate_argnums=0)
    device_batches = [jax.device_put(b) for b in batches]
    seen = set()
    metrics = None
    for b in device_batches:
        sh = (b.node_capacity, b.edge_capacity)
        if sh not in seen:
            seen.add(sh)
            state, metrics = train_step(state, b)
    float(metrics["loss_sum"])
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        n_timed = 2 * len(device_batches)
        for i in range(n_timed):
            k = i % len(device_batches)
            state, metrics = train_step(state, device_batches[k])
        float(metrics["loss_sum"])
        dt = time.perf_counter() - t0
        rate = structs * (n_timed / len(device_batches)) / dt
        best = max(best, rate)
    out["steady_step_structs_per_sec"] = round(best, 1)

    # 1b. production PER-STEP epoch driver (run_epoch: device-side metric
    # accumulation + ONE epoch-end fetch) — the fair per-epoch-semantics
    # baseline: any driver that reports per-epoch metrics pays at least
    # one link sync per epoch
    from cgnn_tpu.train.loop import run_epoch

    state, _ = run_epoch(train_step, state, iter(device_batches), train=True,
                         print_freq=0)
    t0 = time.perf_counter()
    for _ in range(args.epochs):
        state, _ = run_epoch(train_step, state, iter(device_batches),
                             train=True, print_freq=0)
    dt = time.perf_counter() - t0
    out["perstep_epoch_structs_per_sec"] = round(
        structs * args.epochs / dt, 1)

    # 2. scan-epoch driver, production path (run_epoch_pair: train + eval
    # under ONE link sync; empty val set here isolates the train side)
    driver = ScanEpochDriver(
        make_train_step(), make_eval_step(),
        batches, [], np.random.default_rng(0),
    )
    state = driver.warm(state)  # keeps first-compiles out of timed epochs
    driver.timings.clear()
    t0 = time.perf_counter()
    for _ in range(args.epochs):
        state, m, _ = driver.run_epoch_pair(state, first=False)
    dt = time.perf_counter() - t0
    out["scan_epoch_s"] = round(dt / args.epochs, 4)
    out["scan_structs_per_sec"] = round(structs * args.epochs / dt, 1)
    out["scan_vs_steady"] = round(
        out["scan_structs_per_sec"] / out["steady_step_structs_per_sec"], 3
    )
    out["scan_vs_perstep_epoch"] = round(
        out["scan_structs_per_sec"] / out["perstep_epoch_structs_per_sec"],
        3,
    )
    out["per_epoch_timings_ms"] = {
        k: round(v / args.epochs * 1e3, 2)
        for k, v in sorted(driver.timings.items())
        if k.endswith("_s")
    }
    out["dispatches_per_epoch"] = round(
        driver.timings.get("train_dispatches", 0.0) / args.epochs, 1
    )
    print(json.dumps(jsonfinite(out)))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(jsonfinite(out), fh, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
