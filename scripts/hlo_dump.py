#!/usr/bin/env python
"""Dump the optimized HLO of the flagship train step and name the
surviving relayout/copy ops with their byte counts (VERDICT r3 #6).

PERF.md attributes a ~2.16 ms/step "data formatting" residual (~25% of
the step) to XLA/Mosaic layout assignment without an on-disk artifact.
This script produces the artifact: the post-optimization HLO for the
bench-shape train step on the REAL device, plus a ranked table of
copy/transpose/reshape-bearing instructions and their output bytes.

Writes:
  HLO_TRAIN_STEP.txt   full optimized HLO (the evidence)
  prints one JSON line with the ranked formatting ops

Usage: python scripts/hlo_dump.py [--n 8192] [--fused-epilogue off|xla|pallas]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
    "s32": 4, "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def shape_bytes(shape_str: str) -> int:
    """'bf16[6144,12,256]{2,1,0:T(8,128)(2,1)}' -> byte count."""
    m = _SHAPE_RE.match(shape_str)
    if not m:
        return 0
    dt, dims = m.group(1), m.group(2)
    if dt not in _DTYPE_BYTES:
        return 0
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES[dt]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--n", type=int, default=8192)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--fused-epilogue", choices=["off", "xla", "pallas"],
                   default="off")
    p.add_argument("--out", type=str, default="HLO_TRAIN_STEP.txt")
    p.add_argument("--top", type=int, default=20)
    args = p.parse_args(argv)

    import jax
    import numpy as np

    from cgnn_tpu.analysis.program_audit import lower_train_program
    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
    from cgnn_tpu.data.graph import bucketed_batch_iterator
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = load_synthetic_mp(args.n, cfg, seed=0)
    batches = list(bucketed_batch_iterator(
        graphs, args.batch_size, 3, shuffle=True,
        rng=np.random.default_rng(0), dense_m=12, snug=True,
        edge_dtype=jax.numpy.bfloat16,
    ))
    # largest bucket shape = the dominant cost
    batch = max(batches, key=lambda b: b.edge_capacity)
    model = CrystalGraphConvNet(
        atom_fea_len=64, n_conv=3, h_fea_len=128, dtype=jax.numpy.bfloat16,
        dense_m=12,
        fused_epilogue=None if args.fused_epilogue == "off"
        else args.fused_epilogue,
    )
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10**9])
    state = create_train_state(
        model, batch, tx, Normalizer.fit(np.stack([g.target for g in graphs]))
    )
    # ONE lowering path for train programs (ISSUE 8): the same
    # jit_train_step/abstract-aval plumbing graftaudit audits, so the
    # HLO this dumps is byte-for-byte the program the auditor gates
    compiled = lower_train_program(state, batch).compile()
    txt = compiled.as_text()
    with open(args.out, "w") as f:
        f.write(txt)

    # rank formatting instructions: explicit copies/transposes/bitcasts and
    # kLoop fusions whose root is one of those
    findings = []
    for line in txt.splitlines():
        s = line.strip()
        m = re.match(r"%?([\w.\-]+) = (\S+) (copy|transpose|bitcast(?:-convert)?)\(",
                     s)
        if m:
            findings.append({
                "op": m.group(3),
                "name": m.group(1),
                "shape": m.group(2),
                "bytes": shape_bytes(m.group(2)),
            })
    findings.sort(key=lambda d: -d["bytes"])
    total = sum(d["bytes"] for d in findings)
    out = {
        "metric": "hlo_formatting_ops",
        "fused_epilogue": args.fused_epilogue,
        "device": str(jax.devices()[0].device_kind),
        "hlo_file": args.out,
        "hlo_instructions": len(txt.splitlines()),
        "explicit_formatting_ops": len(findings),
        "explicit_formatting_bytes": total,
        "top": findings[: args.top],
    }
    print(json.dumps(jsonfinite(out)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
