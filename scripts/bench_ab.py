#!/usr/bin/env python
"""Interleaved same-process A/B of train-step variants (VERDICT r4 weak #1).

Cross-process throughput comparisons are meaningless on this machine: the
tunnel's throughput varies +-3x run-to-run and drifts over minutes (memory:
the r4 fused-kernel cross-process reading was 17% off its interleaved
truth). This harness times every variant in ONE process with interleaved
rounds on the bench PRIMARY workload, so each round's tunnel conditions hit
all variants equally.

Variants:
- linear_call  — the round-4+ gather_transpose mechanism (current default)
- custom_vjp   — the round-3 mechanism (same transpose math; the main
                 hot-path code delta between BENCH_r03 and BENCH_r04)
- compact      — the round-5 compact-staging step (expansion fused in-step)

Writes BENCH_AB.json and prints it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--n", type=int, default=8192)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--buckets", type=int, default=3)
    p.add_argument("--rounds", type=int, default=6)
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--out", type=str, default="BENCH_AB.json")
    args = p.parse_args(argv)

    import jax
    import numpy as np

    from cgnn_tpu.data.compact import CompactSpec, compact_pack_fn, make_expander
    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
    from cgnn_tpu.data.graph import bucketed_batch_iterator
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.ops import segment
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.step import make_train_step

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = load_synthetic_mp(args.n, cfg, seed=0)
    edge_dtype = jax.numpy.bfloat16

    def make_batches(pack_fn=None):
        return list(
            bucketed_batch_iterator(
                graphs, args.batch_size, args.buckets,
                rng=np.random.default_rng(0), dense_m=12, snug=True,
                edge_dtype=edge_dtype, pack_fn=pack_fn,
            )
        )

    full_batches = make_batches()
    spec = CompactSpec.build(graphs, cfg.gdf(), dense_m=12,
                             edge_dtype=edge_dtype)
    compact_batches = make_batches(compact_pack_fn(spec))
    expander = make_expander(spec)
    structs = [float(np.asarray(b.graph_mask).sum()) for b in full_batches]

    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                dtype=jax.numpy.bfloat16, dense_m=12)
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10**9])
    normalizer = Normalizer.fit(
        np.stack([np.array(g.target) for g in graphs])
    )

    base_step = make_train_step()
    variants = {}
    # batches are inputs, never donated — the two full-layout variants
    # share one device copy (halves batch HBM); compact has its own
    dev_full = [jax.device_put(b) for b in full_batches]
    dev_compact = [jax.device_put(b) for b in compact_batches]
    for name in ("linear_call", "custom_vjp", "compact"):
        dev = dev_compact if name == "compact" else dev_full
        # each variant gets ITS OWN state AND normalizer arrays: donated
        # steps delete state buffers, and jax caches np->device transfers
        # by array id — sharing one Normalizer's numpy arrays across
        # variants means the first variant's donation deletes the cached
        # buffer under the others ("Array has been deleted"; this exact
        # trap broke the r4 A/B harness)
        state = create_train_state(
            model, full_batches[0], tx,
            jax.tree_util.tree_map(np.copy, normalizer),
            rng=jax.random.key(0),
        )
        if name == "compact":
            step_body = lambda s, b: base_step(s, expander(b))  # noqa: E731
        else:
            step_body = base_step
        variants[name] = {
            "dev": dev,
            "state": state,
            "step": jax.jit(step_body, donate_argnums=0),
        }

    # warmup/compile every variant (trace-time transpose impl switch)
    for name, v in variants.items():
        segment.set_transpose_impl(
            "custom_vjp" if name == "custom_vjp" else "linear_call"
        )
        seen = set()
        metrics = None
        for b in v["dev"]:
            k = (b.node_capacity, b.edge_capacity)
            if k not in seen:
                seen.add(k)
                v["state"], metrics = v["step"](v["state"], b)
        v["state"], metrics = v["step"](v["state"], v["dev"][0])
        float(metrics["loss_sum"])
    segment.set_transpose_impl("linear_call")

    # one UNRECORDED burn-in round first (despite per-shape warmup, the
    # first timed executions of a program mix in one-time runtime costs —
    # round 0 was the sole outlier in early runs), then the recorded
    # rounds ROTATE the variant order so monotonic tunnel drift within a
    # round biases each variant equally instead of always the same one
    names = list(variants)
    rounds: list[dict] = []
    for r in range(-1, args.rounds):
        order = names[r % len(names):] + names[: r % len(names)]
        for name in order:
            v = variants[name]
            t0 = time.perf_counter()
            done = 0.0
            metrics = None
            for i in range(args.steps):
                k = i % len(v["dev"])
                v["state"], metrics = v["step"](v["state"], v["dev"][k])
                done += structs[k]
            float(metrics["loss_sum"])  # value-fetch fence
            dt = time.perf_counter() - t0
            if r >= 0:  # round -1 is the discarded burn-in
                rounds.append({"round": r, "variant": name,
                               "dt_s": round(dt, 4),
                               "structs_per_sec": round(done / dt, 1)})

    def rates(name):
        return [e["structs_per_sec"] for e in rounds if e["variant"] == name]

    med = {n: float(np.median(rates(n))) for n in variants}
    spread = {n: [min(rates(n)), max(rates(n))] for n in variants}
    out = {
        "metric": "bench_ab_interleaved",
        "workload": f"MP-like n={args.n} batch={args.batch_size} "
                    f"buckets={args.buckets} dense two-tier bf16",
        "rounds": rounds,
        "median_structs_per_sec": med,
        "round_spread": spread,
        "linear_call_vs_custom_vjp": round(
            med["linear_call"] / med["custom_vjp"], 4
        ),
        "compact_vs_full": round(med["compact"] / med["linear_call"], 4),
        "device": str(jax.devices()[0].device_kind),
        "fencing": "value-fetch per round",
    }
    with open(args.out, "w") as f:
        json.dump(jsonfinite(out), f, indent=1)
    print(json.dumps(jsonfinite(out)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
