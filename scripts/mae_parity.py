#!/usr/bin/env python
"""MAE parity harness: JAX framework vs the in-tree torch CGCNN oracle.

BASELINE.md's acceptance row has two halves: throughput (bench.py) and
"formation-energy MAE <= GPU baseline". The reference tree is unavailable
(SURVEY.md §0), so the GPU baseline is *measured* here by training the
in-tree torch oracle (tests/oracle/torch_cgcnn.py — the lineage
architecture, SURVEY.md §4.3) and the JAX model on the SAME dataset with
the SAME hyperparameters, from independent inits, and comparing test MAE.

Two datasets: ``--dataset tiny`` (8-atom fully-coordinated cells, the
round-2 harness) and ``--dataset mp`` (the MP-like ~30-atom lognormal
distribution INCLUDING under-coordinated structures — the oracle masks
its dense [N, M] padding slots with the exact semantics of the
framework's packing, pinned by tests/test_parity.py
TestMaskedOracleParity at 1e-8).

Prints one JSON line:
  {"torch_oracle_test_mae", "jax_test_mae", "ratio", ...}
Exit code 1 if the JAX model is more than --tolerance worse than the
oracle.

Usage: python scripts/mae_parity.py [--n 1024] [--epochs 50] [--device cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cgnn_tpu.observe.metrics_io import jsonfinite  # noqa: E402


def torch_train_eval(graphs, split, *, epochs, batch_size, lr, seed,
                     max_num_nbr):
    """Train the oracle on (train, val, test) index lists -> test MAE."""
    import numpy as np
    import torch

    from tests.oracle.torch_cgcnn import TorchCGCNN

    train_g, val_g, test_g = split
    m = max_num_nbr
    gdim = graphs[0].edge_fea.shape[1]

    from cgnn_tpu.data.graph import dense_neighbor_views

    def dense_views(g):
        """dense_neighbor_views, cached on the graph: under-coordinated
        nodes (real MP ~30-atom cells) have < M neighbors; their padding
        slots carry mask 0 and are excluded from BN statistics and the
        message sum by the masked oracle — the EXACT semantics of the
        framework's packing."""
        cached = getattr(g, "_dense_views", None)
        if cached is None:
            cached = g._dense_views = dense_neighbor_views(g, m)
        return cached

    def collate(batch_graphs):
        """Lineage-style collate: concat nodes, offset dense [N, M] idx."""
        atom, nbr, idx, masks, ranges, ys = [], [], [], [], [], []
        off = 0
        for g in batch_graphs:
            n = g.num_nodes
            dn, di, dm = dense_views(g)
            atom.append(np.asarray(g.atom_fea, np.float32))
            nbr.append(dn)
            idx.append(di + off)
            masks.append(dm)
            ranges.append(torch.arange(off, off + n))
            ys.append(float(g.target[0]))
            off += n
        return (
            torch.from_numpy(np.concatenate(atom)),
            torch.from_numpy(np.concatenate(nbr)),
            torch.from_numpy(np.concatenate(idx)).long(),
            torch.from_numpy(np.concatenate(masks)),
            ranges,
            torch.tensor(ys, dtype=torch.float32),
        )

    torch.manual_seed(seed)
    model = TorchCGCNN(
        orig_atom_fea_len=graphs[0].atom_fea.shape[1],
        nbr_fea_len=graphs[0].edge_fea.shape[1],
        atom_fea_len=64,
        n_conv=3,
        h_fea_len=128,
        n_h=1,
    )
    opt = torch.optim.Adam(model.parameters(), lr=lr)
    t_mean = float(np.mean([g.target[0] for g in train_g]))
    t_std = float(np.std([g.target[0] for g in train_g]) + 1e-8)

    shuffle_rng = np.random.default_rng(seed)

    def run(split_graphs, train=False):
        model.train(train)
        # one generator across epochs: fresh shuffle each training epoch,
        # matching the JAX loop's reshuffling (train/loop.py)
        order = (shuffle_rng.permutation(len(split_graphs)) if train
                 else np.arange(len(split_graphs)))
        ae_sum = count = 0.0
        for i in range(0, len(order), batch_size):
            bg = [split_graphs[j] for j in order[i:i + batch_size]]
            atom, nbr, idx, mask, ranges, y = collate(bg)
            out = model(atom, nbr, idx, ranges, nbr_mask=mask)[:, 0]
            if train:
                loss = torch.nn.functional.mse_loss(out, (y - t_mean) / t_std)
                opt.zero_grad()
                loss.backward()
                opt.step()
            with torch.no_grad():
                ae_sum += float((out * t_std + t_mean - y).abs().sum())
            count += len(bg)
        return ae_sum / max(count, 1)

    best_val, best_state = float("inf"), None
    for _epoch in range(epochs):
        run(train_g, train=True)
        with torch.no_grad():
            val_mae = run(val_g)
        if val_mae < best_val:
            best_val = val_mae
            best_state = {k: v.clone() for k, v in model.state_dict().items()}
    model.load_state_dict(best_state)
    with torch.no_grad():
        return run(test_g), best_val


def jax_train_eval(split, *, epochs, batch_size, lr, seed,
                   matched_init=False):
    import numpy as np

    import jax

    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import evaluate, fit

    train_g, val_g, test_g = split
    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128, n_h=1)
    tx = make_optimizer(optim="adam", lr=lr, lr_milestones=[10**9])
    normalizer = Normalizer.fit(np.stack([g.target for g in train_g]))
    node_cap, edge_cap = capacities_for(train_g, batch_size)
    example = next(batch_iterator(train_g, batch_size, node_cap, edge_cap))
    state = create_train_state(
        model, example, tx, normalizer, rng=jax.random.key(seed)
    )
    if matched_init:
        # draw the init from the SAME distribution the lineage trains
        # from (torch Linear defaults: kaiming_uniform(a=sqrt(5)) +
        # fan-in uniform bias) by transplanting a fresh UNTRAINED oracle
        # — an independent draw (different torch seed than the oracle
        # run), isolating framework-vs-framework optimization from the
        # flax-lecun_normal vs torch-kaiming init lottery
        import torch

        from tests.oracle.torch_cgcnn import TorchCGCNN, variables_from_torch

        torch.manual_seed(seed + 7919)
        fresh = TorchCGCNN(
            orig_atom_fea_len=train_g[0].atom_fea.shape[1],
            nbr_fea_len=train_g[0].edge_fea.shape[1],
            atom_fea_len=64, n_conv=3, h_fea_len=128, n_h=1,
        )
        variables = variables_from_torch(
            fresh, {"params": state.params, "batch_stats": state.batch_stats}
        )
        state = state.replace(
            params=jax.tree_util.tree_map(
                lambda x: np.asarray(x, np.float32), variables["params"]
            ),
            batch_stats=jax.tree_util.tree_map(
                lambda x: np.asarray(x, np.float32),
                variables["batch_stats"],
            ),
        )
    best = {"params": state.params, "batch_stats": state.batch_stats,
            "val": float("inf")}

    def on_epoch_end(s, _epoch, val_m, is_best):
        if is_best:
            # true host SNAPSHOTS, not just fetches: on CPU, device_get
            # returns views ALIASING the device buffers, which the
            # donated train step mutates in later epochs (the PR-2
            # checkpoint-corruption incident) — without the np.array
            # copy, "best" params silently drift to the last epoch's
            best.update(
                params=jax.tree_util.tree_map(
                    np.array, jax.device_get(s.params)),
                batch_stats=jax.tree_util.tree_map(
                    np.array, jax.device_get(s.batch_stats)),
                val=val_m["mae"])

    state, result = fit(
        state, train_g, val_g, epochs=epochs, batch_size=batch_size,
        node_cap=node_cap, edge_cap=edge_cap, seed=seed, print_freq=0,
        on_epoch_end=on_epoch_end, log_fn=lambda *a, **k: None,
    )
    state = state.replace(params=best["params"], batch_stats=best["batch_stats"])
    test_m = evaluate(state, test_g, batch_size, node_cap, edge_cap)
    return float(test_m["mae"]), float(result["best"])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--n", type=int, default=1024)
    p.add_argument("--epochs", type=int, default=50)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--repeats", type=int, default=1,
                   help="average over this many seeds (seed..seed+R-1); a "
                        "~100-structure test set has ~10%% MAE standard "
                        "error, so single-seed ratios are noise-dominated")
    p.add_argument("--device", choices=["auto", "cpu"], default="auto")
    p.add_argument("--tolerance", type=float, default=0.05,
                   help="max allowed (jax_mae / torch_mae - 1)")
    p.add_argument("--matched-init", action="store_true",
                   help="initialize the JAX model from a fresh UNTRAINED "
                        "torch oracle (independent draw) so both "
                        "frameworks start from the lineage's init "
                        "distribution")
    p.add_argument("--dataset", choices=["tiny", "mp"], default="tiny",
                   help="'mp': the realistic MP-like lognormal ~30-atom "
                        "distribution (radius 6), UNDER-COORDINATED "
                        "structures included — the oracle masks its dense "
                        "padding slots so the comparison is exact "
                        "(VERDICT r2 #4). 'tiny': 8-atom fully-coordinated "
                        "cells (radius 8), the round-2 harness")
    args = p.parse_args(argv)
    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from cgnn_tpu.data.dataset import (
        FeaturizeConfig,
        load_synthetic,
        load_synthetic_mp,
        train_val_test_split,
    )

    if args.dataset == "mp":
        # radius 4.5: ~9% of atoms under-coordinated (radius 6 saturates
        # max_num_nbr on this distribution and would mask nothing)
        cfg = FeaturizeConfig(radius=4.5, max_num_nbr=12)
        full = load_synthetic_mp(args.n, cfg, seed=11)
    else:
        cfg = FeaturizeConfig(radius=8.0, max_num_nbr=12)
        graphs = load_synthetic(args.n, cfg, seed=11, max_atoms=8)
        # round-2 precondition: dense [N, M] layout == flat COO edge set
        # (the masked oracle no longer needs it, kept for comparability)
        full = [
            g for g in graphs
            if np.all(np.bincount(g.centers, minlength=g.num_nodes)
                      == cfg.max_num_nbr)
        ]
        if len(full) < args.n * 0.9:
            print(f"only {len(full)}/{args.n} fully-coordinated structures",
                  file=sys.stderr)
            return 1
    runs = []
    t_torch = t_jax = 0.0
    for seed in range(args.seed, args.seed + args.repeats):
        split = train_val_test_split(full, 0.8, 0.1, seed=seed)
        t0 = time.perf_counter()
        torch_mae, torch_val = torch_train_eval(
            full, split, epochs=args.epochs, batch_size=args.batch_size,
            lr=args.lr, seed=seed, max_num_nbr=cfg.max_num_nbr,
        )
        t_torch += time.perf_counter() - t0
        t0 = time.perf_counter()
        jax_mae, jax_val = jax_train_eval(
            split, epochs=args.epochs, batch_size=args.batch_size,
            lr=args.lr, seed=seed, matched_init=args.matched_init,
        )
        t_jax += time.perf_counter() - t0
        runs.append({"seed": seed,
                     "torch_test_mae": round(torch_mae, 5),
                     "jax_test_mae": round(jax_mae, 5),
                     "torch_val_mae": round(torch_val, 5),
                     "jax_val_mae": round(jax_val, 5)})

    mean_torch = float(np.mean([r["torch_test_mae"] for r in runs]))
    mean_jax = float(np.mean([r["jax_test_mae"] for r in runs]))
    ratio = mean_jax / mean_torch
    # per-seed ratio band: the pooled ratio alone invites over-reading a
    # lucky 2-3-seed draw as superiority (VERDICT r4 weak #3) — report
    # mean +/- sample std so the claim strength is visible in the artifact
    per_seed = [r["jax_test_mae"] / r["torch_test_mae"] for r in runs]
    print(json.dumps(jsonfinite({
        "metric": "formation_energy_mae_parity",
        "dataset": args.dataset,
        "matched_init": bool(args.matched_init),
        "torch_oracle_test_mae": round(mean_torch, 5),
        "jax_test_mae": round(mean_jax, 5),
        "ratio": round(ratio, 4),
        "per_seed_ratios": [round(r, 4) for r in per_seed],
        "ratio_mean": round(float(np.mean(per_seed)), 4),
        "ratio_std": round(
            float(np.std(per_seed, ddof=1)) if len(per_seed) > 1 else 0.0,
            4),
        "repeats": args.repeats,
        "runs": runs,
        "n_structures": len(full),
        "epochs": args.epochs,
        "torch_train_s": round(t_torch, 1),
        "jax_train_s": round(t_jax, 1),
    })))
    return 0 if ratio <= 1.0 + args.tolerance else 1


if __name__ == "__main__":
    sys.exit(main())
