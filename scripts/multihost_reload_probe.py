#!/usr/bin/env python
"""Cross-host coordinated hot-reload probe (ISSUE 10; multihost smoke
leg 2, one process of N).

Run under the ``CGNN_TPU_COORDINATOR``/``_NUM_PROCESSES``/``_PROCESS_ID``
env triple on every process, all pointed at ONE shared checkpoint
directory (leg 1's training output). Each process:

1. restores the newest committed checkpoint into a ParamStore (the
   serving hot-swap holder),
2. lockstep-polls a ``CheckpointWatcher`` wired to
   ``dist.ReloadCoordinator`` — every ``poll_once`` on every process is
   one collective round: process 0 broadcasts the newest committed save
   it sees, non-zero processes wait until their own filesystem view
   shows that save's commit marker, and everyone swaps only after the
   shared barrier;
3. process 0 commits a perturbed save mid-loop (the "trainer published
   new weights" event);
4. prints ``RELOAD_RESULT version=<v> round=<k>`` — the smoke script
   asserts every process reports the SAME version at the SAME round
   (the version-consistent cross-host reload pin).

Exit non-zero if the swap never lands.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    ckpt_dir = sys.argv[1]
    from cgnn_tpu.parallel import dist

    if not dist.initialize_from_env(log_fn=print):
        print("CGNN_TPU_COORDINATOR env triple required", file=sys.stderr)
        return 2
    import jax
    import numpy as np

    from cgnn_tpu.config import build_model
    from cgnn_tpu.data.dataset import load_synthetic
    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.serve.reload import CheckpointWatcher, ParamStore
    from cgnn_tpu.serve.server import plan_from_state
    from cgnn_tpu.train import (
        CheckpointManager,
        Normalizer,
        create_train_state,
        make_optimizer,
    )

    pid = dist.process_index()
    mgr = CheckpointManager(ckpt_dir, log_fn=print)
    meta = mgr.read_meta("latest")
    cfg = plan_from_state(meta)
    model = build_model(cfg["model_cfg"].for_arbitrary_inputs(),
                        cfg["data_cfg"], cfg["task"])
    graphs = load_synthetic(16, cfg["data_cfg"].featurize_config(), seed=0)
    dense_m = cfg["model_cfg"].dense_m or None
    nc, ec = capacities_for(graphs, 8, dense_m=dense_m, snug=True)
    example = next(batch_iterator(graphs, 8, nc, ec, dense_m=dense_m,
                                  in_cap=0, snug=True))
    state = create_train_state(
        model, example, make_optimizer(),
        Normalizer.identity(cfg["model_cfg"].num_targets),
        rng=jax.random.key(0),
    )
    state = mgr.restore_for_inference(state, "latest")
    version = mgr.last_restored or "latest"
    store = ParamStore(state, version)
    watcher = CheckpointWatcher(
        mgr, store, state,
        coordinator=dist.ReloadCoordinator(mgr, log_fn=print),
        log_fn=print,
    )
    print(f"proc {pid}: serving params {store.version}", flush=True)

    swapped_round = -1
    for rnd in range(60):
        if pid == 0 and rnd == 3:
            # the "trainer published new weights" event, process-0-only
            def nudge(x):
                a = np.asarray(x)
                if np.issubdtype(a.dtype, np.floating):
                    return (a * 1.05 + 0.01).astype(a.dtype)
                return a

            new_state = state.replace(
                params=jax.tree_util.tree_map(nudge, state.params))
            mgr.save(new_state, dict(meta, epoch=-1))
            mgr.wait()
            print(f"proc 0: committed {mgr.newest_committed()}", flush=True)
        # LOCKSTEP poll: each round is one collective on every process
        if watcher.poll_once():
            swapped_round = rnd
            break
        time.sleep(0.05)
    dist.barrier("reload-probe-done")
    if swapped_round < 0:
        print(f"proc {pid}: hot reload never landed", file=sys.stderr)
        return 1
    print(f"RELOAD_RESULT version={store.version} round={swapped_round}",
          flush=True)
    mgr.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
